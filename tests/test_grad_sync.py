"""Explicit bucketed/compressed gradient synchronization (ISSUE 2:
parallel/grad_sync.py + training/loop.py `_grad_sync_step`).

The contracts pinned here:

(a) **fp32 parity.** The bucketed reducer computes the SAME real-number
    gradient as the implicit XLA path — layout is a performance fact. The
    reassociation order differs (documented in `_grad_sync_step`): the
    implicit path contracts the loss mean over the global batch inside one
    XLA program; the explicit path sums each shard locally and psums across
    shards (and, under accumulation with overlap, sums per-microbatch psums
    instead of psum-ing one sum). So trajectories match at fp-reassociation
    tolerance (the zero1 precedent), NOT bit-for-bit. What IS bit-for-bit:
    bucket BOUNDARIES (per-element reductions are independent of how the
    flat vector is cut — different bucket_cap_mb, identical trajectory) and
    leaf order within the flat vector (jax.tree_util.tree_leaves order,
    fixed).

(b) **Compressed convergence.** bf16 and int8+error-feedback wires are
    perturbations, not parity: the tiny-LM task must still converge, with
    final loss within the stated tolerance of the fp32 run, and the int8
    residual buffers must actually carry feedback (non-zero after a step).

(c) **The HLO census.** The compiled bucketed step carries at most
    ceil(total_grad_bytes / bucket_cap) + 2 gradient-sized collectives, and
    compressed modes put bf16/s8 on the wire (bf16 read from the
    PRE-optimization HLO — the CPU backend's float-normalization pass
    promotes bf16 collectives to f32 in the optimized text; TPU keeps them).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jax.sharding import PartitionSpec as P

from distributed_pytorch_training_tpu.models.gpt2 import GPT2LMHead
from distributed_pytorch_training_tpu.parallel import (
    MeshSpec, build_mesh, shard_batch,
)
from distributed_pytorch_training_tpu.parallel.collectives import shard_map
from distributed_pytorch_training_tpu.parallel.grad_sync import (
    build_bucket_plan, flatten_tree, padded_bucket_bounds, padded_total_size,
    reduce_flat, unflatten_tree, wire_bytes_per_replica,
)
from distributed_pytorch_training_tpu.training import TrainConfig, Trainer
from distributed_pytorch_training_tpu.training.optim import adamw, sgd
from distributed_pytorch_training_tpu.training.tasks import LanguageModelingTask

SEQ = 16
VOCAB = 64


def _tiny_gpt2():
    return GPT2LMHead(vocab_size=VOCAB, hidden_dim=32, depth=2, num_heads=2,
                      max_position=SEQ)


def _trainer(mesh, opt="sgd", **cfg):
    t = Trainer(LanguageModelingTask(), mesh, TrainConfig(seed=0, **cfg))
    tx = (sgd(0.1, momentum=0.9, weight_decay=5e-4) if opt == "sgd"
          else adamw(1e-2, grad_clip_norm=1.0))
    state = t.init_state(_tiny_gpt2(), np.zeros((1, SEQ), np.int32), tx,
                         jax.random.PRNGKey(0))
    return t, state


def _batch(mesh, n=16, pad_tail=0):
    rng = np.random.RandomState(0)
    w = np.ones(n, np.float32)
    if pad_tail:
        w[-pad_tail:] = 0.0
    return shard_batch({
        "input_ids": rng.randint(0, VOCAB, (n, SEQ)).astype(np.int32),
        "weight": w,
    }, mesh)


def _run(mesh, steps=4, opt="sgd", pad_tail=0, **cfg):
    """(per-step losses, final state) for one config."""
    t, s = _trainer(mesh, opt=opt, **cfg)
    batch = _batch(mesh, pad_tail=pad_tail)
    key = jax.random.PRNGKey(1)
    losses = []
    for _ in range(steps):
        s, m = t._train_step(s, batch, key)
        losses.append(float(m["loss_sum"]) / max(float(m["weight"]), 1.0))
    return losses, s


def _assert_params_close(a, b, **tol):
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_allclose(
            np.asarray(jax.device_get(x)), np.asarray(jax.device_get(y)),
            **tol),
        a.params, b.params)


# ---------------------------------------------------------------------------
# Unit: bucket plan + flatten/unflatten
# ---------------------------------------------------------------------------


class TestBucketPlan:
    def test_cap_and_coverage(self):
        tree = {"a": np.zeros((100, 7)), "b": np.zeros(33),
                "c": np.zeros((5, 5, 5))}
        total = 100 * 7 + 33 + 125
        cap_mb = 400 * 4 / (1024 ** 2)  # a 400-fp32-element cap, in MB
        plan = build_bucket_plan(tree, cap_mb)
        assert plan.total_size == total
        assert plan.bounds[0] == 0 and plan.bounds[-1] == total
        assert plan.n_buckets == -(-total // 400)  # the exact ceil bound
        assert all(s <= 400 for s in plan.bucket_sizes())
        assert sum(plan.bucket_sizes()) == total

    def test_no_cap_is_one_bucket(self):
        plan = build_bucket_plan({"a": np.zeros(1000)}, 0.0)
        assert plan.n_buckets == 1
        huge = build_bucket_plan({"a": np.zeros(1000)}, 100.0)
        assert huge.n_buckets == 1

    def test_flatten_unflatten_roundtrip(self):
        rng = np.random.RandomState(0)
        tree = {"w": jnp.asarray(rng.randn(13, 4), jnp.float32),
                "b": jnp.asarray(rng.randn(9), jnp.float32),
                "s": jnp.asarray(rng.randn(2, 3, 2), jnp.float32)}
        flat = flatten_tree(tree)
        assert flat.shape == (13 * 4 + 9 + 12,)
        back = unflatten_tree(flat, tree)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                       np.asarray(b)),
            tree, back)


# ---------------------------------------------------------------------------
# Parity (contract a)
# ---------------------------------------------------------------------------


@pytest.mark.slow  # ~9 s; bucketing parity stays fast via the adamw leg and bucket-boundaries test
def test_bucketed_fp32_matches_implicit(mesh8):
    l_imp, s_imp = _run(mesh8)
    l_b, s_b = _run(mesh8, bucket_cap_mb=0.05)
    np.testing.assert_allclose(l_imp, l_b, rtol=2e-5)
    _assert_params_close(s_imp, s_b, rtol=1e-4, atol=1e-6)
    assert l_b[-1] < l_b[0]


def test_bucket_boundaries_do_not_change_math(mesh8):
    """Cutting the flat vector differently must be BIT-identical: the
    per-element reductions don't see the boundaries."""
    l_a, s_a = _run(mesh8, steps=3, bucket_cap_mb=0.05)
    l_b, s_b = _run(mesh8, steps=3, bucket_cap_mb=0.004)
    assert l_a == l_b
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_array_equal(
            np.asarray(jax.device_get(x)), np.asarray(jax.device_get(y))),
        s_a.params, s_b.params)


def test_bucketed_padded_batch_rows(mesh8):
    """Weight-0 rows (the loader's padded final batch) recombine by weight
    exactly as on the implicit path."""
    l_imp, _ = _run(mesh8, steps=2, pad_tail=4)
    l_b, _ = _run(mesh8, steps=2, pad_tail=4, bucket_cap_mb=0.05)
    np.testing.assert_allclose(l_imp, l_b, rtol=2e-5)


@pytest.mark.slow
def test_grad_accum_overlap_parity(mesh8):
    """grad_accum=2: implicit scan path vs bucketed with in-scan overlap vs
    bucketed post-scan reduction — one trajectory, three schedules."""
    l_imp, s_imp = _run(mesh8, steps=3, grad_accum=2)
    l_ov, s_ov = _run(mesh8, steps=3, grad_accum=2, bucket_cap_mb=0.05)
    l_no, s_no = _run(mesh8, steps=3, grad_accum=2, bucket_cap_mb=0.05,
                      overlap_grad_sync=False)
    np.testing.assert_allclose(l_imp, l_ov, rtol=2e-5)
    np.testing.assert_allclose(l_imp, l_no, rtol=2e-5)
    _assert_params_close(s_imp, s_ov, rtol=1e-4, atol=1e-6)
    _assert_params_close(s_ov, s_no, rtol=1e-4, atol=1e-6)


def test_bucketed_adamw_matches_implicit(mesh8):
    """AdamW (clip active, NO shard_axes — grads arrive globally synced):
    the optimizer chain must see the same gradient as the implicit path."""
    l_imp, s_imp = _run(mesh8, opt="adamw")
    l_b, s_b = _run(mesh8, opt="adamw", bucket_cap_mb=0.05)
    np.testing.assert_allclose(l_imp, l_b, rtol=2e-5)
    # zero-gradient elements amplify reassociation noise through Adam's
    # normalization (the test_zero1 tolerance argument, verbatim)
    _assert_params_close(s_imp, s_b, rtol=2e-2, atol=2e-3)


# ---------------------------------------------------------------------------
# Compressed convergence (contract b)
# ---------------------------------------------------------------------------


@pytest.mark.slow  # ~7 s convergence smoke; bf16 wire lowering stays gated fast by the gsync_bf16/zero1_bf16 matrix contracts
def test_bf16_wire_converges(mesh8):
    l_fp, _ = _run(mesh8, steps=6)
    l_bf, _ = _run(mesh8, steps=6, bucket_cap_mb=0.05, wire_dtype="bf16")
    assert l_bf[-1] < l_bf[0]
    # bf16 wire rounding perturbs each step by ~2^-8 relative — the
    # trajectory stays within 1% of fp32 on this task
    np.testing.assert_allclose(l_fp, l_bf, rtol=1e-2)


@pytest.mark.slow  # ~10 s convergence smoke; int8 EF exactness stays fast via the multihop 20-step parity + pre-EF resume legs
def test_int8_ef_converges_and_feedback_engages(mesh8):
    l_fp, _ = _run(mesh8, steps=8)
    l_i8, s_i8 = _run(mesh8, steps=8, bucket_cap_mb=0.05, wire_dtype="int8")
    assert l_i8[-1] < l_i8[0]
    # int8 is coarse per step but error feedback telescopes the bias; the
    # loss trajectory tracks fp32 within 2% on this task
    np.testing.assert_allclose(l_fp, l_i8, rtol=2e-2)
    # the residual buffers must be alive (all-zero EF = quantization
    # claimed exact = feedback not wired)
    ef = np.asarray(jax.device_get(s_i8.grad_sync["ef"]))
    assert ef.shape[0] == 8  # one residual row per replica
    assert np.abs(ef).max() > 0.0


@pytest.mark.slow
def test_int8_ef_checkpoint_roundtrip(mesh8, tmp_path):
    """The EF residual IS trajectory state: a resume that zeroes it
    re-introduces the bias error feedback exists to cancel. Orbax must
    round-trip TrainState.grad_sync exactly and the restored run must
    continue the trajectory bit-for-bit."""
    from distributed_pytorch_training_tpu.training.checkpoint import (
        CheckpointManager,
    )

    batch = _batch(mesh8)
    key = jax.random.PRNGKey(1)
    t, state = _trainer(mesh8, bucket_cap_mb=0.05, wire_dtype="int8")
    state, _ = t._train_step(state, batch, key)
    assert np.abs(np.asarray(
        jax.device_get(state.grad_sync["ef"]))).max() > 0.0

    ckpt = CheckpointManager(str(tmp_path / "ckpt"))
    ckpt.save(1, state, wait=True)
    t2, template = _trainer(mesh8, bucket_cap_mb=0.05, wire_dtype="int8")
    restored, _, _ = ckpt.restore_latest(template)
    ckpt.close()
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(state.grad_sync["ef"])),
        np.asarray(jax.device_get(restored.grad_sync["ef"])))
    s_a, m_a = t._train_step(state, batch, key)
    s_b, m_b = t2._train_step(restored, batch, key)
    np.testing.assert_array_equal(np.asarray(m_a["loss_sum"]),
                                  np.asarray(m_b["loss_sum"]))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b))),
        s_a.params, s_b.params)


def test_int8_resume_from_pre_ef_checkpoint(mesh8, tmp_path):
    """Turning --wire-dtype int8 ON over an existing (EF-less) checkpoint
    must resume, not crash: orbax rejects template keys the checkpoint
    lacks, so restore_latest drops the grad_sync entry for legacy
    checkpoints and error feedback restarts from zero residuals."""
    from distributed_pytorch_training_tpu.training.checkpoint import (
        CheckpointManager,
    )

    batch = _batch(mesh8)
    key = jax.random.PRNGKey(1)
    t_fp, s_fp = _trainer(mesh8)  # the legacy run: no EF state
    s_fp, _ = t_fp._train_step(s_fp, batch, key)
    ckpt = CheckpointManager(str(tmp_path / "ckpt"))
    ckpt.save(1, s_fp, wait=True)

    t_i8, template = _trainer(mesh8, bucket_cap_mb=0.05, wire_dtype="int8")
    restored, _, _ = ckpt.restore_latest(template)
    ckpt.close()
    ef = np.asarray(jax.device_get(restored.grad_sync["ef"]))
    assert np.all(ef == 0.0)  # fresh telescopes
    s2, m = t_i8._train_step(restored, batch, key)
    assert np.isfinite(float(m["loss_sum"]))


def test_int8_requires_init_state_ef_buffers(mesh8):
    """A state built without Trainer.init_state has no EF buffers — the
    step must fail loudly, not silently skip feedback."""
    t, s = _trainer(mesh8, bucket_cap_mb=0.05, wire_dtype="int8")
    s_no_ef = s.replace(grad_sync={})
    with pytest.raises(ValueError, match="error-feedback"):
        t._train_step(s_no_ef, _batch(mesh8), jax.random.PRNGKey(1))


# ---------------------------------------------------------------------------
# Multi-hop int8 wire (ISSUE 4: the DynamiQ n-independent codec)
# ---------------------------------------------------------------------------


def _multihop_reduce_fn(mesh, plan, n=8):
    """jitted (contribs (n, S), ef (n, R)) -> (sums (n, S), new ef): the
    multihop codec run inside a shard_map over the test mesh, one
    contribution row per replica."""
    def body(x, ef):
        out, new_ef = reduce_flat(x.reshape(-1), plan, ("data",), n,
                                  "int8_multihop", ef.reshape(-1))
        return out[None], new_ef[None]

    return jax.jit(shard_map(body, mesh, in_specs=(P("data"), P("data")),
                             out_specs=(P("data"), P("data"))))


class TestMultihopCodec:
    """Unit contracts of `_int8_multihop_sum` via `reduce_flat` on the
    8-device CPU mesh (real collectives, no cluster)."""

    S = 1000  # not divisible by 8 — exercises the padded-to-n layout
    CAP = 400 * 4 / (1024 ** 2)  # 400-element buckets: sizes 400/400/200

    def _plan(self):
        return build_bucket_plan({"a": np.zeros(self.S)}, self.CAP)

    def test_exact_on_grid_values(self, mesh8):
        """Contributions that sit exactly on both hops' quantization grids
        (integer values, every destination chunk's max-abs pinned to 127 so
        the per-chunk scale is exactly 1 and the hop-2 scale exactly n)
        must round-trip bit-exactly with an all-zero residual — any
        deviation is codec math, not quantization."""
        plan = self._plan()
        rng = np.random.RandomState(0)
        row = rng.randint(-127, 128, self.S).astype(np.float32)
        row[::10] = 127.0  # every >=25-element chunk sees max-abs 127
        contribs = np.tile(row, (8, 1))
        ef0 = np.zeros((8, padded_total_size(plan, 8)), np.float32)
        out, ef = _multihop_reduce_fn(mesh8, plan)(contribs, ef0)
        np.testing.assert_array_equal(np.asarray(out)[0], 8.0 * row)
        np.testing.assert_array_equal(np.asarray(ef), 0.0)

    def test_one_shot_error_is_bounded_by_quanta(self, mesh8):
        """|multihop - exact| <= sum of the senders' hop-1 half-quanta plus
        the hop-2 half-quantum — the two-quantization error model PARITY.md
        documents, asserted instead of hand-waved."""
        plan = self._plan()
        rng = np.random.RandomState(1)
        contribs = rng.randn(8, self.S).astype(np.float32)
        exact = contribs.sum(0)
        ef0 = np.zeros((8, padded_total_size(plan, 8)), np.float32)
        out, ef = _multihop_reduce_fn(mesh8, plan)(contribs, ef0)
        out = np.asarray(out)[0]
        bounds = padded_bucket_bounds(plan, 8)
        for k, (a, b) in enumerate(zip(plan.bounds, plan.bounds[1:])):
            chunk = (bounds[k + 1] - bounds[k]) // 8
            seg = slice(a, b)
            # hop-1: each sender's per-destination-chunk scale; hop-2: the
            # owner's partial-sum scale. Conservative per-bucket bound.
            hop1 = 8 * (np.abs(contribs[:, seg]).max() / 127.0) / 2
            hop2 = (np.abs(exact[seg]).max() + hop1) / 127.0 / 2
            err = np.abs(out[seg] - exact[seg]).max()
            assert err <= hop1 + hop2 + 1e-5, (k, err, hop1, hop2, chunk)
        # and the hop-1 residual is alive (error feedback engaged)
        assert np.abs(np.asarray(ef)).max() > 0.0

    def test_hop1_error_feedback_telescopes(self, mesh8):
        """Repeated reduction of the SAME contributions: the hop-1 bias
        telescopes (each step's residual is re-injected), so the cumulative
        MEAN converges well below the one-shot error — what remains is the
        un-fed-back hop-2 noise, bounded by one quantum. A codec that drops
        its residual keeps the full one-shot bias at every horizon and
        fails both assertions."""
        plan = self._plan()
        rng = np.random.RandomState(2)
        contribs = rng.randn(8, self.S).astype(np.float32)
        exact = contribs.sum(0)
        f = _multihop_reduce_fn(mesh8, plan)
        ef = np.zeros((8, padded_total_size(plan, 8)), np.float32)
        out1, _ = f(contribs, np.zeros_like(ef))
        one_shot = np.abs(np.asarray(out1)[0] - exact).max()
        cum = np.zeros(self.S)
        steps = 12
        for _ in range(steps):
            out, ef = f(contribs, ef)
            cum += np.asarray(out)[0]
        mean_err = np.abs(cum / steps - exact).max()
        quantum = 8 * np.abs(contribs).max() / 127.0
        assert mean_err < one_shot / 2, (mean_err, one_shot)
        assert mean_err <= quantum, (mean_err, quantum)


def test_multihop_parity_20_steps(mesh8):
    """ISSUE-4 acceptance: fp32-vs-multihop loss trajectories agree within
    tolerance over >= 20 steps on the CPU mesh (grad-accum OFF; the two
    quantizations are bounded per step and hop-1 telescopes)."""
    l_fp, _ = _run(mesh8, steps=20)
    l_mh, s_mh = _run(mesh8, steps=20, bucket_cap_mb=0.05,
                      wire_dtype="int8_multihop")
    assert l_mh[-1] < l_mh[0]
    np.testing.assert_allclose(l_fp, l_mh, rtol=3e-2)
    # hop-1 residuals: per-replica rows in the padded-to-n layout
    plan = build_bucket_plan(s_mh.params, 0.05)
    ef = np.asarray(jax.device_get(s_mh.grad_sync["ef"]))
    assert ef.shape == (8, padded_total_size(plan, 8))
    assert np.abs(ef).max() > 0.0


@pytest.mark.slow  # ~9 s; the non-accum multihop parity stays fast and the accum interaction is gated by the gsync_int8_mh_accum matrix contract
def test_multihop_parity_20_steps_grad_accum(mesh8):
    """ISSUE-4 acceptance, grad-accum ON: the residual is carried through
    the microbatch scan (each in-scan reduction quantizes and feeds back)
    and the trajectory still tracks fp32. Twice the reductions per step =
    twice the hop-2 perturbations, and by step ~18 this tiny high-LR task
    is chaotic enough that fp32 itself swings ~15% per step — so the
    per-step bound is coarse (no gross divergence) and the time-averaged
    tail, where the noise washes out, carries the tight bound."""
    l_fp, _ = _run(mesh8, steps=20, grad_accum=2)
    l_mh, _ = _run(mesh8, steps=20, grad_accum=2, bucket_cap_mb=0.05,
                   wire_dtype="int8_multihop")
    assert l_mh[-1] < l_mh[0]
    np.testing.assert_allclose(l_fp, l_mh, rtol=1.5e-1)
    np.testing.assert_allclose(np.mean(l_fp[-5:]), np.mean(l_mh[-5:]),
                               rtol=2e-2)


@pytest.mark.slow
def test_multihop_no_overlap_matches_overlap(mesh8):
    """Post-scan reduction vs in-scan overlap: same per-step reductions in
    a different schedule position — trajectories agree at the compressed
    tolerance (EF sees different carried values, so not bit-equal)."""
    l_ov, _ = _run(mesh8, steps=6, grad_accum=2, bucket_cap_mb=0.05,
                   wire_dtype="int8_multihop")
    l_no, _ = _run(mesh8, steps=6, grad_accum=2, bucket_cap_mb=0.05,
                   wire_dtype="int8_multihop", overlap_grad_sync=False)
    assert l_no[-1] < l_no[0]
    np.testing.assert_allclose(l_ov, l_no, rtol=3e-2)


def test_multihop_requires_init_state_ef_buffers(mesh8):
    t, s = _trainer(mesh8, bucket_cap_mb=0.05, wire_dtype="int8_multihop")
    s_no_ef = s.replace(grad_sync={})
    with pytest.raises(ValueError, match="error-feedback"):
        t._train_step(s_no_ef, _batch(mesh8), jax.random.PRNGKey(1))


def test_multihop_rejects_residual_from_other_bucket_plan(mesh8):
    """The multihop residual lives in the padded layout of ITS bucket plan:
    a state restored under a different bucket_cap_mb must be rejected
    loudly (silently slicing the old residual at new offsets would
    re-inject stale error at the wrong elements)."""
    t_big, s_big = _trainer(mesh8, bucket_cap_mb=0.05,
                            wire_dtype="int8_multihop")
    t_small, _ = _trainer(mesh8, bucket_cap_mb=0.004,
                          wire_dtype="int8_multihop")
    with pytest.raises(ValueError, match="different bucket plan"):
        t_small._train_step(s_big, _batch(mesh8), jax.random.PRNGKey(1))


def test_zero1_multihop_parity_20_steps(mesh8):
    """The ROADMAP composition, landed: zero1 + int8_multihop = the s8
    all-to-all scatter (error feedback, as under wire_dtype='int8') PLUS
    the s8 delta-quantized param all-gather. 20-step fp32-parity at
    lr=0.05 — at the default high-LR 0.1 this tiny task goes chaotic by
    step ~17 (the grad-accum multihop test documents the same tail), so
    the parity run uses the saner LR where divergence measures the wire,
    not the Lyapunov exponent."""
    def run(wire):
        t = Trainer(LanguageModelingTask(), mesh8,
                    TrainConfig(seed=0, zero1=True, wire_dtype=wire))
        s = t.init_state(_tiny_gpt2(), np.zeros((1, SEQ), np.int32),
                         sgd(0.05, momentum=0.9, weight_decay=5e-4),
                         jax.random.PRNGKey(0))
        batch = _batch(mesh8)
        key = jax.random.PRNGKey(1)
        losses = []
        for _ in range(20):
            s, m = t._train_step(s, batch, key)
            losses.append(float(m["loss_sum"])
                          / max(float(m["weight"]), 1.0))
        return losses, s

    l_fp, s_fp = run("fp32")
    l_mh, s_mh = run("int8_multihop")
    assert l_mh[-1] < l_mh[0]
    np.testing.assert_allclose(l_fp, l_mh, rtol=3e-2)
    _assert_params_close(s_fp, s_mh, rtol=5e-2, atol=5e-3)
    # params must stay exactly replicated: every replica dequantized the
    # SAME (codes, scales) onto the same replicated old params
    wte = s_mh.params["wte"]["embedding"]
    assert wte.sharding.is_fully_replicated
    # the scatter half's EF residuals exist and engaged (per-leaf zero1
    # layout: (n, padded) rows)
    ef_leaves = jax.tree_util.tree_leaves(s_mh.grad_sync["ef"])
    assert ef_leaves and all(l.shape[0] == 8 for l in ef_leaves)
    assert max(float(jnp.abs(l).max()) for l in ef_leaves) > 0.0


@pytest.mark.slow  # ~9 s; strictly redundant with the zero1_int8_mh contract in the matrix gate (same census, same rules)
def test_zero1_multihop_census_all_s8_no_checker_relaxation(mesh8):
    """BOTH halves off fp32 in the lowered HLO: the gradient-sized wire is
    s8 all-to-all (scatter) + s8 all-gather (the delta-compressed param
    gather) with NO gradient-sized fp32 collective left — checked with the
    same census the analysis matrix runs (zero1_int8_mh contract), no rule
    relaxed."""
    from distributed_pytorch_training_tpu.analysis.hlo_rules import (
        grad_sync_census, preopt_hlo_text,
    )

    lowered, _, _ = _lower(mesh8, zero1=True, wire_dtype="int8_multihop")
    census = grad_sync_census(preopt_hlo_text(lowered), min_elements=128)
    assert census["by_op"].get("all-to-all", 0) > 0     # s8 scatter half
    assert census["by_op"].get("all-gather", 0) > 0     # s8 delta gather
    assert census["wire_dtypes"].get("s8", 0) == census["n_collectives"]
    assert "f32" not in census["wire_dtypes"]
    assert "bf16" not in census["wire_dtypes"]


class TestWireBytesAccounting:
    """`wire_bytes_per_replica`: the mode table's byte formulas as code."""

    def _plan(self, total=4096, bucket=1024):
        # bucket sizes divisible by 8 -> zero multihop padding at n<=8,
        # so the n-independence assertion below is exact, not approximate
        return build_bucket_plan({"a": np.zeros(total)},
                                 bucket * 4 / (1024 ** 2))

    def test_multihop_bytes_independent_of_n(self):
        plan = self._plan()
        vals = {n: wire_bytes_per_replica(plan, "int8_multihop", n)
                for n in (2, 4, 8)}
        assert len(set(vals.values())) == 1, vals
        assert vals[2] == 2 * plan.total_size  # ~2 B/element, flat in n

    def test_gather_int8_grows_and_breaks_even_at_9(self):
        plan = self._plan()
        s = plan.total_size
        assert [wire_bytes_per_replica(plan, "int8", n)
                for n in (2, 4, 8)] == [s, 3 * s, 7 * s]
        # the documented break-even: at n=9 the gather form's (n-1)*S
        # equals fp32's 8*S, while multihop still moves 2*S
        assert wire_bytes_per_replica(plan, "int8", 9) == \
            wire_bytes_per_replica(plan, "fp32", 9)
        assert wire_bytes_per_replica(plan, "int8_multihop", 9) < \
            wire_bytes_per_replica(plan, "int8", 9)

    def test_float_wires_and_passthrough(self):
        plan = self._plan()
        assert wire_bytes_per_replica(plan, "fp32", 8) == 8 * plan.total_size
        assert wire_bytes_per_replica(plan, "bf16", 8) == 4 * plan.total_size
        assert wire_bytes_per_replica(plan, "bf16", 1) == 0  # passthrough
        with pytest.raises(ValueError, match="unknown wire dtype"):
            wire_bytes_per_replica(plan, "int4", 8)

    def test_padded_layout_bounds(self):
        plan = build_bucket_plan({"a": np.zeros(1000)},
                                 400 * 4 / (1024 ** 2))  # 400/400/200
        assert padded_bucket_bounds(plan, 8) == (0, 400, 800, 1000)
        assert padded_bucket_bounds(plan, 3) == (0, 402, 804, 1005)
        assert padded_total_size(plan, 3) == 1005


# ---------------------------------------------------------------------------
# HLO census (contract c — the ISSUE 2 acceptance check)
# ---------------------------------------------------------------------------


def _lower(mesh, **cfg):
    t, s = _trainer(mesh, **cfg)
    lowered = t._train_step.lower(s, _batch(mesh), jax.random.PRNGKey(1))
    return lowered, lowered.compile().as_text(), s


@pytest.mark.slow  # ~7 s; strictly redundant with the gsync_fp32 contract in the matrix gate
def test_census_bucket_bound_fp32(mesh8):
    from distributed_pytorch_training_tpu.experiments.trace_analysis import (
        grad_sync_census, verify_grad_sync_collectives,
    )

    cap = 0.02  # ~5.2k fp32 elements per bucket
    lowered, opt_text, state = _lower(mesh8, bucket_cap_mb=cap)
    plan = build_bucket_plan(state.params, cap)
    assert plan.n_buckets > 1  # the bound must actually bind
    verdict = verify_grad_sync_collectives(
        opt_text, total_grad_bytes=plan.total_bytes, bucket_cap_mb=cap,
        wire_dtype="fp32", min_elements=128)
    assert verdict["census"]["n_collectives"] <= plan.n_buckets + 2
    # and the wire is fp32
    assert verdict["wire"].get("f32", 0) > 0
    # the one-per-leaf implicit baseline for comparison (informational:
    # XLA may combine, so only sanity-check it found SOME collectives)
    _, imp_text, _ = _lower(mesh8)
    assert grad_sync_census(imp_text, min_elements=128)["n_collectives"] > 0


def test_census_bf16_on_the_wire(mesh8):
    from distributed_pytorch_training_tpu.experiments.trace_analysis import (
        preopt_hlo_text, verify_grad_sync_collectives,
    )

    cap = 0.05
    lowered, opt_text, state = _lower(mesh8, bucket_cap_mb=cap,
                                      wire_dtype="bf16")
    plan = build_bucket_plan(state.params, cap)
    verify_grad_sync_collectives(
        opt_text, total_grad_bytes=plan.total_bytes, bucket_cap_mb=cap,
        wire_dtype="bf16", wire_text=preopt_hlo_text(lowered),
        min_elements=128)


def test_census_int8_on_the_wire(mesh8):
    from distributed_pytorch_training_tpu.experiments.trace_analysis import (
        verify_grad_sync_collectives,
    )

    cap = 0.05
    lowered, opt_text, state = _lower(mesh8, bucket_cap_mb=cap,
                                      wire_dtype="int8")
    plan = build_bucket_plan(state.params, cap)
    # s8 survives even the optimized text (no float-normalization for ints)
    verify_grad_sync_collectives(
        opt_text, total_grad_bytes=plan.total_bytes, bucket_cap_mb=cap,
        wire_dtype="int8", min_elements=128)


@pytest.mark.slow  # ~5 s; strictly redundant with the gsync_int8_mh contract in the matrix gate
def test_census_int8_multihop_two_per_bucket(mesh8):
    """ISSUE-4 acceptance: the compiled multihop step carries exactly
    2 x ceil(bytes/cap) gradient-sized collectives (+slack 2) with the
    two-hop signature (all-to-all + all-gather) and s8 — never f32 — on
    the gradient wire."""
    from distributed_pytorch_training_tpu.experiments.trace_analysis import (
        grad_sync_census, verify_grad_sync_collectives,
    )

    cap = 0.02
    lowered, opt_text, state = _lower(mesh8, bucket_cap_mb=cap,
                                      wire_dtype="int8_multihop")
    plan = build_bucket_plan(state.params, cap)
    assert plan.n_buckets > 1  # the bound must actually bind
    verdict = verify_grad_sync_collectives(
        opt_text, total_grad_bytes=plan.total_bytes, bucket_cap_mb=cap,
        wire_dtype="int8_multihop", min_elements=128)
    census = verdict["census"]
    assert verdict["bound"] == 2 * plan.n_buckets + 2
    assert census["n_collectives"] == 2 * plan.n_buckets
    # the hop signature: one s8 all-to-all + one s8 all-gather per bucket
    assert census["by_op"].get("all-to-all") == plan.n_buckets
    assert census["by_op"].get("all-gather") == plan.n_buckets
    # s8 survives the optimized text (no float-normalization for ints);
    # no f32 rides any gradient-sized collective
    assert census["wire_dtypes"].get("s8") == census["n_collectives"]
    assert "f32" not in census["wire_dtypes"]


def test_census_rejects_unengaged_bucketing(mesh8):
    """The verifier must FAIL when handed an implicit-path step whose
    collective count exceeds the bucket bound — that is its whole job."""
    from distributed_pytorch_training_tpu.experiments.trace_analysis import (
        grad_sync_census, verify_grad_sync_collectives,
    )

    _, imp_text, state = _lower(mesh8)
    plan = build_bucket_plan(state.params, 1.0)  # 1 bucket for this model
    n_implicit = grad_sync_census(imp_text, min_elements=128)["n_collectives"]
    if n_implicit <= plan.n_buckets + 2:
        pytest.skip("XLA combined the implicit path below the bound here")
    with pytest.raises(AssertionError, match="bucketing is not engaged"):
        verify_grad_sync_collectives(
            imp_text, total_grad_bytes=plan.total_bytes, bucket_cap_mb=1.0,
            min_elements=128)


# ---------------------------------------------------------------------------
# zero1 composition (the reduce-scatter halves compress)
# ---------------------------------------------------------------------------


@pytest.mark.slow  # ~10 s; bf16 wire and zero1 are each pinned fast separately (bf16 converges, zero1 multihop parity)
def test_zero1_bf16_wire_matches_zero1_fp32(mesh8):
    from distributed_pytorch_training_tpu.experiments.trace_analysis import (
        grad_sync_census, preopt_hlo_text,
    )

    l_z, s_z = _run(mesh8, zero1=True)
    l_zb, s_zb = _run(mesh8, zero1=True, wire_dtype="bf16")
    assert l_zb[-1] < l_zb[0]
    np.testing.assert_allclose(l_z, l_zb, rtol=1e-2)
    _assert_params_close(s_z, s_zb, rtol=1e-2, atol=1e-3)
    # the reduce-scatter half really runs at bf16 (pre-optimization HLO;
    # CPU promotes in the optimized text)
    lowered, _, _ = _lower(mesh8, zero1=True, wire_dtype="bf16")
    wire = grad_sync_census(preopt_hlo_text(lowered),
                            min_elements=128)["wire_dtypes"]
    assert wire.get("bf16", 0) > 0, wire


@pytest.mark.slow
def test_zero1_int8_wire_trains(mesh8):
    l_zi, s_zi = _run(mesh8, steps=6, zero1=True, wire_dtype="int8")
    assert l_zi[-1] < l_zi[0]
    ef_leaves = jax.tree_util.tree_leaves(s_zi.grad_sync["ef"])
    assert ef_leaves and all(l.shape[0] == 8 for l in ef_leaves)
    assert max(float(jnp.abs(l).max()) for l in ef_leaves) > 0.0


@pytest.mark.slow
def test_zero1_int8_grad_accum_trains(mesh8):
    """EF residuals carried through the microbatch scan (the zero1 accum
    path scatters per microbatch — each scatter quantizes and feeds back)."""
    l, _ = _run(mesh8, steps=4, zero1=True, wire_dtype="int8", grad_accum=2)
    assert l[-1] < l[0]


# ---------------------------------------------------------------------------
# Engagement / rejection
# ---------------------------------------------------------------------------


def test_single_shard_is_passthrough(devices):
    mesh1 = build_mesh(MeshSpec(data=1), devices=devices[:1])
    t = Trainer(LanguageModelingTask(), mesh1,
                TrainConfig(seed=0, bucket_cap_mb=25.0, wire_dtype="bf16"))
    assert not t._grad_sync  # nothing to synchronize on one shard
    s = t.init_state(_tiny_gpt2(), np.zeros((1, SEQ), np.int32),
                     sgd(0.1), jax.random.PRNGKey(0))
    s, m = t._train_step(s, _batch(mesh1, n=4), jax.random.PRNGKey(1))
    assert np.isfinite(float(m["loss_sum"]))


def test_zero1_takes_priority_over_bucketing_conflict(mesh8):
    """zero1 + bucket_cap is a layout contradiction (zero1's per-leaf
    flat shards ARE its optimizer-state format) — loud failure."""
    with pytest.raises(ValueError, match="bucket_cap_mb"):
        Trainer(LanguageModelingTask(), mesh8,
                TrainConfig(zero1=True, bucket_cap_mb=25.0))


def test_rejects_unknown_wire_dtype(mesh8):
    with pytest.raises(ValueError, match="wire_dtype"):
        Trainer(LanguageModelingTask(), mesh8,
                TrainConfig(wire_dtype="fp8"))


def test_rejects_non_dp_meshes(devices):
    mesh = build_mesh(MeshSpec(data=4, model=2), devices=devices)
    with pytest.raises(ValueError, match="grad_sync"):
        Trainer(LanguageModelingTask(), mesh,
                TrainConfig(bucket_cap_mb=25.0))


def test_rejects_sharded_param_rules(devices):
    mesh = build_mesh(MeshSpec(data=2, fsdp=4), devices=devices)
    with pytest.raises(ValueError, match="fsdp"):
        Trainer(LanguageModelingTask(), mesh,
                TrainConfig(bucket_cap_mb=25.0),
                rules=GPT2LMHead.partition_rules())
