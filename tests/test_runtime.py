"""Runtime layer: per-rank seed rule + DistContext basics.

The reference de-correlates host RNG across ranks with `seed + rank`
(/root/reference/train_ddp.py:76-78); the TPU design keeps device-side keys
shared (SPMD traces must agree) but host-side streams must follow the rule.
"""

import numpy as np

from distributed_pytorch_training_tpu.runtime import (
    per_process_seed, set_seed, setup_distributed,
)


def test_per_process_seed_matches_reference_rule():
    # the exact seed+rank arithmetic of ref :76-78
    for rank in range(4):
        assert per_process_seed(42, rank) == 42 + rank


def test_set_seed_decorrelates_processes():
    rng0 = set_seed(42, process_index=0)
    draw0 = rng0.integers(0, 2**31, 16)
    np0 = np.random.randint(0, 2**31, 16)  # global numpy stream, rank 0

    rng1 = set_seed(42, process_index=1)
    draw1 = rng1.integers(0, 2**31, 16)
    np1 = np.random.randint(0, 2**31, 16)  # global numpy stream, rank 1

    assert not np.array_equal(draw0, draw1), "per-rank streams must differ"
    assert not np.array_equal(np0, np1), "global numpy stream must differ too"

    # and the rule is reproducible: same (seed, rank) -> same stream
    again = set_seed(42, process_index=1).integers(0, 2**31, 16)
    np.testing.assert_array_equal(draw1, again)


def test_set_seed_rank_uses_runtime_process_index():
    # single-process runtime: default rank is 0 -> identical to explicit 0
    a = set_seed(7).integers(0, 2**31, 8)
    b = set_seed(7, process_index=0).integers(0, 2**31, 8)
    np.testing.assert_array_equal(a, b)


def test_setup_distributed_single_process_context():
    ctx = setup_distributed()
    assert ctx.process_index == 0
    assert ctx.process_count == 1
    assert ctx.is_main


def test_persistent_compile_cache_refuses_cpu_backend(tmp_path):
    """XLA:CPU persistent-cache reloads are unsafe (AOT pseudo-feature
    mismatch desynchronized a collective rendezvous into a fatal abort —
    runtime.dist.enable_persistent_compile_cache docstring). On the CPU
    test backend the helper must refuse (in the default "auto" mode) and
    leave the config untouched."""
    import jax

    from distributed_pytorch_training_tpu.runtime import (
        enable_persistent_compile_cache,
    )

    before = jax.config.jax_compilation_cache_dir
    assert enable_persistent_compile_cache(tmp_path / "cache") is False
    assert jax.config.jax_compilation_cache_dir == before
    assert not (tmp_path / "cache").exists()


def test_compile_cache_tristate(tmp_path, monkeypatch):
    """ISSUE-11: the DPT_COMPILE_CACHE tri-state — "off" never enables,
    "on" forces (the operator vouches), invalid values are loud, unset
    resolves to "auto" (the backend-gated historical behavior)."""
    import jax
    import pytest

    from distributed_pytorch_training_tpu.runtime import (
        COMPILE_CACHE_ENV, compile_cache_mode,
        enable_persistent_compile_cache,
    )

    dir_before = jax.config.jax_compilation_cache_dir
    min_before = jax.config.jax_persistent_cache_min_compile_time_secs

    monkeypatch.setenv(COMPILE_CACHE_ENV, "off")
    assert compile_cache_mode() == "off"
    assert enable_persistent_compile_cache(tmp_path / "c") is False
    assert jax.config.jax_compilation_cache_dir == dir_before

    monkeypatch.setenv(COMPILE_CACHE_ENV, "maybe")
    with pytest.raises(ValueError, match="DPT_COMPILE_CACHE"):
        compile_cache_mode()

    monkeypatch.delenv(COMPILE_CACHE_ENV, raising=False)
    assert compile_cache_mode() == "auto"
    assert compile_cache_mode("on") == "on"  # explicit arg beats the env

    try:
        assert enable_persistent_compile_cache(tmp_path / "c",
                                               mode="on") is True
        assert jax.config.jax_compilation_cache_dir == str(tmp_path / "c")
    finally:
        jax.config.update("jax_compilation_cache_dir", dir_before)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          min_before)


def test_compile_cache_dir_is_keyed_and_sanitized(tmp_path):
    """(topology, config) key one directory each; key components become
    filesystem-safe tokens."""
    from distributed_pytorch_training_tpu.runtime import compile_cache_dir

    a = compile_cache_dir(tmp_path, "cpu-8dev", "gpt2 124m/zero1")
    b = compile_cache_dir(tmp_path, "cpu-4dev", "gpt2 124m/zero1")
    c = compile_cache_dir(tmp_path, "cpu-8dev", "gpt2 124m/fsdp")
    assert len({a, b, c}) == 3
    assert a.parent == b.parent == tmp_path
    for p in (a, b, c):
        assert "/" not in p.name and " " not in p.name
