"""Pipeline parallelism (parallel/pipeline.py): GPipe schedule over the
``pipe`` mesh axis must match sequential layer application — forward AND
backward (autodiff through scan+ppermute is the reverse schedule)."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_training_tpu.parallel import MeshSpec, build_mesh
from distributed_pytorch_training_tpu.parallel.pipeline import (
    init_stacked_layers,
    pipeline_apply,
    sequential_apply,
    stack_to_stages,
)


class TinyLayer(nn.Module):
    dim: int = 8

    @nn.compact
    def __call__(self, x):
        return x + nn.Dense(self.dim)(nn.gelu(x))


@pytest.fixture(scope="module")
def layer_setup(devices):
    layer = TinyLayer()
    x = jnp.asarray(np.random.RandomState(0).randn(8, 4, 8), jnp.float32)
    stacked = init_stacked_layers(layer, jax.random.PRNGKey(1), x[:1], 4)

    def apply_layer(params, h):
        return layer.apply({"params": params}, h)

    return layer, x, stacked, apply_layer


def test_pipeline_matches_sequential_forward(devices, layer_setup):
    _, x, stacked, apply_layer = layer_setup
    mesh = build_mesh(MeshSpec(pipe=2, data=4), devices=devices)
    stage_params = stack_to_stages(stacked, 2)

    want = sequential_apply(apply_layer, stacked, x)
    got = pipeline_apply(apply_layer, stage_params, x, mesh,
                         num_microbatches=2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_matches_sequential_grad(devices, layer_setup):
    _, x, stacked, apply_layer = layer_setup
    mesh = build_mesh(MeshSpec(pipe=4, data=2), devices=devices)
    stage_params = stack_to_stages(stacked, 4)

    def loss_pipe(sp):
        y = pipeline_apply(apply_layer, sp, x, mesh, num_microbatches=2)
        return (y ** 2).sum()

    def loss_seq(st):
        return (sequential_apply(apply_layer, st, x) ** 2).sum()

    g_pipe = jax.grad(loss_pipe)(stage_params)
    g_seq = stack_to_stages(jax.grad(loss_seq)(stacked), 4)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4),
        g_pipe, g_seq)


def test_single_stage_degenerates_to_scan(devices, layer_setup):
    _, x, stacked, apply_layer = layer_setup
    mesh = build_mesh(MeshSpec(data=8), devices=devices)
    stage_params = stack_to_stages(stacked, 1)
    want = sequential_apply(apply_layer, stacked, x)
    got = pipeline_apply(apply_layer, stage_params, x, mesh,
                         num_microbatches=4)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
