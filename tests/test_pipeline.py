"""Pipeline parallelism (parallel/pipeline.py): GPipe schedule over the
``pipe`` mesh axis must match sequential layer application — forward AND
backward (autodiff through scan+ppermute is the reverse schedule)."""

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_training_tpu.parallel import MeshSpec, build_mesh
from distributed_pytorch_training_tpu.parallel.pipeline import (
    init_stacked_layers,
    pipeline_apply,
    sequential_apply,
    stack_to_stages,
)


class TinyLayer(nn.Module):
    dim: int = 8

    @nn.compact
    def __call__(self, x):
        return x + nn.Dense(self.dim)(nn.gelu(x))


@pytest.fixture(scope="module")
def layer_setup(devices):
    layer = TinyLayer()
    x = jnp.asarray(np.random.RandomState(0).randn(8, 4, 8), jnp.float32)
    stacked = init_stacked_layers(layer, jax.random.PRNGKey(1), x[:1], 4)

    def apply_layer(params, h):
        return layer.apply({"params": params}, h)

    return layer, x, stacked, apply_layer


def test_pipeline_matches_sequential_forward(devices, layer_setup):
    _, x, stacked, apply_layer = layer_setup
    mesh = build_mesh(MeshSpec(pipe=2, data=4), devices=devices)
    stage_params = stack_to_stages(stacked, 2)

    want = sequential_apply(apply_layer, stacked, x)
    got = pipeline_apply(apply_layer, stage_params, x, mesh,
                         num_microbatches=2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_pipeline_matches_sequential_grad(devices, layer_setup):
    _, x, stacked, apply_layer = layer_setup
    mesh = build_mesh(MeshSpec(pipe=4, data=2), devices=devices)
    stage_params = stack_to_stages(stacked, 4)

    def loss_pipe(sp):
        y = pipeline_apply(apply_layer, sp, x, mesh, num_microbatches=2)
        return (y ** 2).sum()

    def loss_seq(st):
        return (sequential_apply(apply_layer, st, x) ** 2).sum()

    g_pipe = jax.grad(loss_pipe)(stage_params)
    g_seq = stack_to_stages(jax.grad(loss_seq)(stacked), 4)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4),
        g_pipe, g_seq)


def test_single_stage_degenerates_to_scan(devices, layer_setup):
    _, x, stacked, apply_layer = layer_setup
    mesh = build_mesh(MeshSpec(data=8), devices=devices)
    stage_params = stack_to_stages(stacked, 1)
    want = sequential_apply(apply_layer, stacked, x)
    got = pipeline_apply(apply_layer, stage_params, x, mesh,
                         num_microbatches=4)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


# ---- real-model pipeline: GPT-2 through GPipe with an optimizer ----------
# (VERDICT r2 #7: the pipeline had only an 8-wide toy Dense driver)

def _pipe_gpt2(mesh, microbatches=2, depth=4):
    from distributed_pytorch_training_tpu.models.gpt2_pipe import GPT2PipeLMHead
    return GPT2PipeLMHead(mesh=mesh, num_microbatches=microbatches,
                          vocab_size=64, hidden_dim=32, depth=depth,
                          num_heads=2, max_position=16)


def _lm_batch(mesh, n=8, seq=16, vocab=64):
    from distributed_pytorch_training_tpu.parallel import shard_batch
    rng = np.random.RandomState(0)
    return shard_batch({
        "input_ids": rng.randint(0, vocab, (n, seq)).astype(np.int32),
        "weight": np.ones(n, np.float32),
    }, mesh)


@pytest.mark.slow
def test_pipelined_gpt2_matches_sequential_gpt2(devices):
    """Same weights -> same logits: the pipelined model restacked from a
    plain GPT2LMHead's params must reproduce its forward exactly (up to fp
    reassociation)."""
    from distributed_pytorch_training_tpu.models.gpt2 import GPT2LMHead

    mesh = build_mesh(MeshSpec(pipe=2, data=4), devices=devices)
    seq_model = GPT2LMHead(vocab_size=64, hidden_dim=32, depth=4, num_heads=2,
                           max_position=16)
    ids = np.asarray(_lm_batch(mesh)["input_ids"])
    ref_vars = seq_model.init(jax.random.PRNGKey(0), ids[:1], train=False)
    ref_logits = seq_model.apply(ref_vars, ids, train=False)

    # restack block0..block3 params into the (stages, layers/stage, ...) tree
    rp = ref_vars["params"]
    blocks = [rp[f"block{i}"] for i in range(4)]
    stacked = jax.tree_util.tree_map(lambda *ls: jnp.stack(ls), *blocks)
    stage_params = jax.tree_util.tree_map(
        lambda leaf: leaf.reshape(2, 2, *leaf.shape[1:]), stacked)
    pipe_model = _pipe_gpt2(mesh)
    pipe_vars = {"params": {
        "wte": {"embedding": rp["wte"]["embedding"]},
        "wpe": {"embedding": rp["wpe"]["embedding"]},
        "blocks": stage_params,
        "ln_f": {"scale": rp["ln_f"]["scale"], "bias": rp["ln_f"]["bias"]},
    }}
    pipe_logits = pipe_model.apply(pipe_vars, jnp.asarray(ids), train=False)
    np.testing.assert_allclose(np.asarray(pipe_logits),
                               np.asarray(ref_logits), rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_pipelined_training_step_decreases_loss(devices):
    """A full TRAINING step through the pipeline: Trainer + AdamW + GPipe
    forward/backward; loss must decrease and stage params must stay sharded
    over `pipe`."""
    from distributed_pytorch_training_tpu.models.gpt2_pipe import GPT2PipeLMHead
    from distributed_pytorch_training_tpu.training import TrainConfig, Trainer
    from distributed_pytorch_training_tpu.training.optim import adamw
    from distributed_pytorch_training_tpu.training.tasks import (
        LanguageModelingTask,
    )

    mesh = build_mesh(MeshSpec(pipe=2, data=4), devices=devices)
    model = _pipe_gpt2(mesh)
    trainer = Trainer(LanguageModelingTask(), mesh, TrainConfig(seed=0),
                      rules=GPT2PipeLMHead.partition_rules())
    state = trainer.init_state(model, np.zeros((1, 16), np.int32),
                               adamw(1e-2), jax.random.PRNGKey(0))

    # stage params actually ride the pipe axis
    qkv = state.params["blocks"]["attn"]["qkv"]["kernel"]
    assert qkv.sharding.spec[0] == "pipe", qkv.sharding.spec
    assert qkv.addressable_shards[0].data.shape[0] == 1  # 1 of 2 stages

    batch = _lm_batch(mesh)
    key = jax.random.PRNGKey(1)
    losses = []
    for _ in range(8):
        state, metrics = trainer._train_step(state, batch, key)
        losses.append(float(metrics["loss_sum"]) / float(metrics["weight"]))
    assert np.isfinite(losses).all(), losses
    assert losses[-1] < losses[0], losses
    assert int(state.step) == 8


@pytest.mark.slow
def test_pipelined_remat_matches_plain(devices):
    """jax.checkpoint inside pipeline stages changes memory, not math."""
    mesh = build_mesh(MeshSpec(pipe=2, data=4), devices=devices)
    plain = _pipe_gpt2(mesh)
    variables = plain.init(jax.random.PRNGKey(0), np.zeros((1, 16), np.int32))
    remat = dataclasses.replace(plain, remat=True)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (8, 16)))
    np.testing.assert_allclose(
        np.asarray(plain.apply(variables, ids)),
        np.asarray(remat.apply(variables, ids)), rtol=1e-5, atol=1e-5)
