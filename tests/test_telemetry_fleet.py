"""Fleet-wide observability plane (ISSUE 14): the aggregator's multi-
gen/multi-rank merge (clock skew, missing streams, appended generations),
the straggler detector's rank+phase attribution of an injected
loader_stall, the stitched Perfetto timeline's pid/tid stability, the
live /metrics + /healthz endpoint's scrape contract, and the
StreamFollower's rotation-surviving tail.
"""

import json
import re
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import pytest

from distributed_pytorch_training_tpu import telemetry
from distributed_pytorch_training_tpu.telemetry.__main__ import (
    main as telemetry_main, read_stream,
)
from distributed_pytorch_training_tpu.telemetry.aggregate import (
    StreamFollower,
    aggregate_streams,
    detect_stragglers,
    last_step_of,
    split_streams,
    stitch_perfetto,
)


REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _clean_global_recorder():
    telemetry.reset()
    yield
    telemetry.reset()


def _write_stream(path, gen, rank, *, anchor_ts, steps, stall_at=None,
                  stall_s=1.5, dispatch_s=0.004, append=False,
                  start_step=0, gauges=(), epoch_counter=True):
    """A synthetic per-rank stream with the train loop's real shape:
    per-step data_wait + step_dispatch spans (step-stamped), then the
    epoch totals. ``anchor_ts`` simulates each host's own (possibly
    skewed) wall clock."""
    mode = "a" if append else "w"
    ts = anchor_ts
    with open(path, mode, encoding="utf-8") as f:
        def emit(kind, name, **fields):
            ev = {"v": 2, "ts": fields.pop("ts", ts), "kind": kind,
                  "name": name, "gen": gen, "rank": rank, **fields}
            f.write(json.dumps(ev, sort_keys=True) + "\n")

        emit("meta", "stream", schema=2, run_id=f"g{gen}r{rank}",
             pid=1000 + 10 * gen + rank)
        wall = 0.0
        for i in range(steps):
            step = start_step + i
            wait = stall_s if step == stall_at else 0.001
            ts = anchor_ts + wall + wait
            emit("span", "data_wait", t0=anchor_ts + wall,
                 dur_ms=wait * 1e3, step=step)
            wall += wait
            ts = anchor_ts + wall + dispatch_s
            emit("span", "step_dispatch", t0=anchor_ts + wall,
                 dur_ms=dispatch_s * 1e3, step=step)
            wall += dispatch_s
        for name, value in gauges:
            emit("gauge", name, value=value)
        if epoch_counter:
            emit("counter", "epoch_time_s", value=wall, epoch=0)
            emit("counter", "steps", value=steps, epoch=0)
        emit("counter", "wire_bytes_per_replica", value=1024 * steps,
             tier="ici", axis="data")
    return Path(path)


# ---------------------------------------------------------------------------
# aggregator
# ---------------------------------------------------------------------------


class TestAggregate:
    def test_multi_rank_merge_with_clock_skew(self, tmp_path):
        """Two ranks whose host clocks disagree by 1000s merge into one
        summary with side-by-side splits; the skew never reaches the
        comparison (durations are monotonic pairs, timelines re-anchor
        per segment)."""
        p0 = _write_stream(tmp_path / "telemetry_rank0.jsonl", 0, 0,
                           anchor_ts=1_000.0, steps=10)
        p1 = _write_stream(tmp_path / "telemetry_rank1.jsonl", 0, 1,
                           anchor_ts=2_000.0, steps=10)  # +1000s skew
        agg = aggregate_streams([p0, p1])
        assert agg["n_streams"] == 2
        assert agg["identities"] == [(0, 0), (0, 1)]
        assert [s["steps"] for s in agg["streams"]] == [10.0, 10.0]
        # identical workloads -> no straggler from the skew alone
        assert agg["stragglers"] == []
        # wire rollup sums across ranks, keyed by (name, tier, axis)
        (row,) = agg["wire"]
        assert row["tier"] == "ici" and row["axis"] == "data"
        assert row["total"] == 2 * 1024 * 10

    def test_one_stream_missing_is_reported_not_fatal(self, tmp_path):
        p0 = _write_stream(tmp_path / "telemetry_rank0.jsonl", 0, 0,
                           anchor_ts=0.0, steps=4)
        agg = aggregate_streams([p0, tmp_path / "telemetry_rank1.jsonl"])
        assert agg["n_streams"] == 1
        assert agg["missing_streams"] == [
            str(tmp_path / "telemetry_rank1.jsonl")]

    def test_overlapping_generations_in_one_appended_file(self, tmp_path):
        """The elastic-fleet shape: generation 1 APPENDS to the same
        telemetry_rank0.jsonl after a relaunch, re-running overlapping
        steps. The aggregator splits at the meta headers and reports both
        segments separately, attributably."""
        p = tmp_path / "telemetry_rank0.jsonl"
        _write_stream(p, 0, 0, anchor_ts=10.0, steps=8)
        _write_stream(p, 1, 0, anchor_ts=60.0, steps=8, start_step=4,
                      append=True)  # overlaps steps 4..7
        segments = split_streams([p])
        assert [seg.key for seg in segments] == [(0, 0), (1, 0)]
        agg = aggregate_streams([p])
        assert agg["identities"] == [(0, 0), (1, 0)]
        assert [s["gen"] for s in agg["streams"]] == [0, 1]

    def test_aggregate_cli_json(self, tmp_path, capsys):
        p0 = _write_stream(tmp_path / "a.jsonl", 0, 0, anchor_ts=0.0,
                           steps=4)
        p1 = _write_stream(tmp_path / "b.jsonl", 1, 0, anchor_ts=5.0,
                           steps=4)
        assert telemetry_main(["aggregate", str(p0), str(p1),
                               "--json"]) == 0
        agg = json.loads(capsys.readouterr().out)
        assert agg["kind"] == "fleet_summary" and agg["n_streams"] == 2
        # human-readable form renders too
        assert telemetry_main(["aggregate", str(p0), str(p1)]) == 0
        assert "gen=1 rank=0" in capsys.readouterr().out
        # nothing readable -> exit 1
        assert telemetry_main(["aggregate",
                               str(tmp_path / "nope.jsonl")]) == 1

    def test_aggregate_output_path_honored_without_json_flag(
            self, tmp_path, capsys):
        """-o always writes the machine-readable body — a silently
        ignored output path strands every script that reads it."""
        p0 = _write_stream(tmp_path / "a.jsonl", 0, 0, anchor_ts=0.0,
                           steps=4)
        out = tmp_path / "fleet.json"
        assert telemetry_main(["aggregate", str(p0),
                               "-o", str(out)]) == 0
        assert json.loads(out.read_text())["kind"] == "fleet_summary"
        # the human-readable summary still printed to stdout
        assert "gen=0 rank=0" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# straggler detection
# ---------------------------------------------------------------------------


class TestStragglerDetector:
    def test_one_rank_stall_is_rank_and_phase_attributed(self, tmp_path):
        """The acceptance shape: rank 1 takes a 1.5s loader stall at step
        6; the detector names the rank, the step AND the phase, against
        its peers at the same step."""
        p0 = _write_stream(tmp_path / "r0.jsonl", 0, 0, anchor_ts=0.0,
                           steps=12)
        p1 = _write_stream(tmp_path / "r1.jsonl", 0, 1, anchor_ts=0.0,
                           steps=12, stall_at=6)
        stragglers = detect_stragglers(split_streams([p0, p1]))
        assert len(stragglers) == 1
        s = stragglers[0]
        assert (s["gen"], s["rank"], s["step"], s["phase"]) == \
            (0, 1, 6, "data_wait")
        assert s["basis"] == "peers_at_step" and s["peers"] == 1
        assert s["dur_s"] == pytest.approx(1.5)

    def test_solo_segment_stall_falls_back_to_phase_median(self, tmp_path):
        """Elastic overlap is partial: a stalled step no peer ran is
        still attributed, against the phase's own cross-fleet median."""
        p0 = _write_stream(tmp_path / "g0.jsonl", 0, 0, anchor_ts=0.0,
                           steps=8)
        p1 = _write_stream(tmp_path / "g1.jsonl", 1, 0, anchor_ts=50.0,
                           steps=4, start_step=20, stall_at=22)
        (s,) = detect_stragglers(split_streams([p0, p1]))
        assert (s["gen"], s["step"], s["phase"]) == (1, 22, "data_wait")
        assert s["basis"] == "phase_median"

    def test_first_dispatch_compile_is_not_a_straggler(self, tmp_path):
        """Every relaunch's first step_dispatch carries the compile; the
        detector's warm-up exemption keeps cold starts out of the
        straggler table (data_wait has no such exemption)."""
        p0 = _write_stream(tmp_path / "r0.jsonl", 0, 0, anchor_ts=0.0,
                           steps=10)
        # rank 1's first dispatch is 3s (the compile), rest normal
        p1 = tmp_path / "r1.jsonl"
        _write_stream(p1, 0, 1, anchor_ts=0.0, steps=0,
                      epoch_counter=False)
        with open(p1, "a") as f:
            for i in range(10):
                f.write(json.dumps({
                    "v": 2, "ts": float(i), "kind": "span",
                    "name": "step_dispatch", "t0": float(i),
                    "dur_ms": 3000.0 if i == 0 else 4.0, "step": i,
                    "gen": 0, "rank": 1}) + "\n")
        assert detect_stragglers(split_streams([p0, p1])) == []

    def test_microsecond_noise_stays_below_the_floor(self, tmp_path):
        """5x spread at sub-floor absolute durations is CPU-mesh noise,
        not divergence."""
        p0 = _write_stream(tmp_path / "r0.jsonl", 0, 0, anchor_ts=0.0,
                           steps=10, dispatch_s=0.001)
        p1 = _write_stream(tmp_path / "r1.jsonl", 0, 1, anchor_ts=0.0,
                           steps=10, dispatch_s=0.02)  # 20x but 20ms
        assert detect_stragglers(split_streams([p0, p1])) == []

    def test_injected_loader_stall_through_the_real_loop(self, tmp_path,
                                                        mesh8):
        """End to end through the REAL instrumented train loop: two
        mock-step epochs over the chaos rig, one with a loader_stall
        fault injected into its ShardedLoader — the merged view must
        attribute (gen=1, data_wait, the stalled step)."""
        import jax.numpy as jnp

        from distributed_pytorch_training_tpu.data.loader import (
            ShardedLoader,
        )
        from distributed_pytorch_training_tpu.resilience.__main__ import (
            _build_rig,
        )
        from distributed_pytorch_training_tpu.resilience.faults import (
            FaultInjector, FaultPlan,
        )

        metrics = {"loss_sum": jnp.float32(1.0),
                   "correct": jnp.float32(1.0),
                   "weight": jnp.float32(16.0)}

        def run_child(gen, stream_path, fault_hook=None):
            trainer, state_factory, loader = _build_rig(
                mesh8, seed=0, dataset_size=320, per_device_batch=2)
            trainer._train_step = lambda state, batch, key: (state,
                                                             metrics)
            if fault_hook is not None:
                loader = ShardedLoader(loader.dataset, trainer.mesh, 2,
                                       shuffle=True, seed=0,
                                       fault_hook=fault_hook)
            telemetry.configure(str(stream_path), gen=gen, rank=0)
            spe = len(loader)
            trainer.train_epoch(None, loader.epoch(0), 0, spe,
                                samples_per_step=[16] * spe)
            telemetry.reset()

        p0 = tmp_path / "clean.jsonl"
        p1 = tmp_path / "stalled.jsonl"
        run_child(0, p0)
        injector = FaultInjector(
            FaultPlan.parse("loader_stall@step=8:0.6s"))
        run_child(1, p1, fault_hook=injector.on_loader_batch)
        assert injector.fired == ["loader_stall@step=8:0.6s"]

        agg = aggregate_streams([p0, p1])
        hits = [s for s in agg["stragglers"]
                if s["phase"] == "data_wait" and s["gen"] == 1]
        assert hits, agg["stragglers"]
        assert hits[0]["dur_s"] >= 0.5
        # and the clean child is never blamed
        assert all(s["gen"] == 1 for s in agg["stragglers"])


# ---------------------------------------------------------------------------
# stitched Perfetto timeline
# ---------------------------------------------------------------------------


class TestStitchedPerfetto:
    def _streams(self, tmp_path):
        p = tmp_path / "telemetry_rank0.jsonl"
        _write_stream(p, 0, 0, anchor_ts=1_000.0, steps=4,
                      gauges=[("world_size", 8)])
        _write_stream(p, 1, 0, anchor_ts=9_000.0, steps=4, append=True,
                      gauges=[("world_size", 4)])
        q = _write_stream(tmp_path / "telemetry_rank1.jsonl", 0, 1,
                          anchor_ts=5_000.0, steps=4)
        return [p, q]

    def test_one_stable_pid_per_gen_rank(self, tmp_path):
        paths = self._streams(tmp_path)
        trace = stitch_perfetto(split_streams(paths))
        names = {e["args"]["name"]: e["pid"]
                 for e in trace["traceEvents"] if e["ph"] == "M"}
        # exactly one pid/tid pair per (gen, rank), deterministically
        # ordered by identity
        assert names == {"gen0/rank0": 1, "gen0/rank1": 2,
                         "gen1/rank0": 3}
        span_keys = {(e["pid"], e["tid"])
                     for e in trace["traceEvents"] if e["ph"] == "X"}
        assert span_keys == {(1, 1), (2, 1), (3, 1)}
        # stability: re-stitching (and reversing the file order) maps the
        # same identities to the same pids
        again = stitch_perfetto(split_streams(list(reversed(paths))))
        names2 = {e["args"]["name"]: e["pid"]
                  for e in again["traceEvents"] if e["ph"] == "M"}
        assert names2 == names

    def test_skew_normalized_to_each_meta_anchor(self, tmp_path):
        """Anchors 1000s/5000s/9000s apart overlay near t=0: no span
        starts more than the segment's own duration from zero."""
        trace = stitch_perfetto(split_streams(self._streams(tmp_path)))
        spans = [e for e in trace["traceEvents"] if e["ph"] == "X"]
        assert spans and all(0 <= e["ts"] < 60 * 1e6 for e in spans)
        # the absolute wall clock survives in args for cross-referencing
        assert all("wall_ts" in e["args"] for e in spans)

    def test_gauges_become_counter_tracks(self, tmp_path):
        trace = stitch_perfetto(split_streams(self._streams(tmp_path)))
        counters = [e for e in trace["traceEvents"] if e["ph"] == "C"]
        assert {e["name"] for e in counters} == {"world_size"}
        assert {e["args"]["value"] for e in counters} == {8.0, 4.0}

    def test_multi_stream_export_cli(self, tmp_path):
        paths = self._streams(tmp_path)
        out = tmp_path / "trace.json"
        assert telemetry_main(["export", str(paths[0]), str(paths[1]),
                               "--perfetto", "-o", str(out)]) == 0
        trace = json.loads(out.read_text())
        pids = {e["pid"] for e in trace["traceEvents"]}
        assert pids == {1, 2, 3}


# ---------------------------------------------------------------------------
# /metrics + /healthz
# ---------------------------------------------------------------------------


_PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? -?[0-9.eE+na-f]+$")


def _scrape(port, path="/metrics"):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=5) as resp:
        return resp.status, resp.read().decode()


class TestMetricsEndpoint:
    def test_scrape_is_prometheus_parseable_and_advances(self, tmp_path):
        rec = telemetry.configure(str(tmp_path / "t.jsonl"))
        server = telemetry.MetricsServer(0, recorder=rec)
        port = server.start()
        try:
            rec.span_event("step_dispatch", 0.004, step=0)
            rec.span_event("data_wait", 0.001, step=0)
            rec.counter("epoch_time_s", 0.005, epoch=0)
            rec.counter("wire_bytes_per_replica", 2048, tier="ici",
                        axis="data")
            rec.counter("tp_psum_bytes_per_replica", 512, tier="ici",
                        axis="model")
            rec.gauge("world_size", 8)
            rec.anomaly("loader_stall", step=3)
            status, body = _scrape(port)
            assert status == 200
            for line in body.strip().splitlines():
                if line.startswith("#"):
                    continue
                assert _PROM_LINE.match(line), line
            assert "dpt_steps_total 1" in body
            assert "dpt_last_step 0" in body
            assert "dpt_epoch 0" in body
            assert ('dpt_phase_seconds_count{phase="step_dispatch"} 1'
                    in body)
            assert ('dpt_wire_bytes_total{name="wire_bytes_per_replica"'
                    ',tier="ici",axis="data"} 2048') in body
            # the 2-D tier axis rolls in as one more label value
            assert 'axis="model"} 512' in body
            assert 'dpt_anomalies_total{name="loader_stall"} 1' in body
            assert 'dpt_gauge{name="world_size"} 8' in body
            # counters ADVANCE across scrapes while steps keep landing
            rec.span_event("step_dispatch", 0.004, step=1)
            _, body2 = _scrape(port)
            assert "dpt_steps_total 2" in body2
            assert "dpt_last_step 1" in body2
        finally:
            server.stop()

    def test_healthz_flips_when_the_fence_stops(self, tmp_path):
        rec = telemetry.configure(str(tmp_path / "t.jsonl"))
        server = telemetry.MetricsServer(0, recorder=rec,
                                         stale_after_s=0.4)
        port = server.start()
        try:
            rec.span_event("step_dispatch", 0.004, step=0)
            status, body = _scrape(port, "/healthz")
            assert status == 200 and json.loads(body)["healthy"] is True
            time.sleep(0.6)   # the fence stops advancing
            with pytest.raises(urllib.error.HTTPError) as err:
                _scrape(port, "/healthz")
            assert err.value.code == 503
            detail = json.loads(err.value.read().decode())
            assert detail["healthy"] is False
            assert detail["last_progress_age_s"] >= 0.4
            # progress resumes -> healthy again
            rec.span_event("step_dispatch", 0.004, step=1)
            status, _ = _scrape(port, "/healthz")
            assert status == 200
        finally:
            server.stop()

    def test_off_means_zero_new_threads(self, tmp_path):
        """The zero-when-off contract: an unset/zero port starts nothing
        — no listener, no observer, no thread."""
        before = set(threading.enumerate())
        assert telemetry.resolve_metrics_port(None) == 0
        assert telemetry.resolve_metrics_port(0) == 0
        assert telemetry.start_metrics_server(0) is None
        rec = telemetry.configure(str(tmp_path / "t.jsonl"))
        rec.span_event("step_dispatch", 0.004, step=0)
        assert set(threading.enumerate()) == before
        assert rec._observers == []

    def test_port_resolution_env_and_rank_offset(self, monkeypatch):
        monkeypatch.delenv(telemetry.METRICS_PORT_ENV, raising=False)
        assert telemetry.resolve_metrics_port(None, rank=3) == 0
        assert telemetry.resolve_metrics_port(9200, rank=3) == 9203
        monkeypatch.setenv(telemetry.METRICS_PORT_ENV, "9100")
        assert telemetry.resolve_metrics_port(None, rank=2) == 9102
        # explicit CLI beats the env
        assert telemetry.resolve_metrics_port(9300, rank=0) == 9300

    def test_replayed_step_is_not_progress(self, tmp_path):
        """A restart loop re-dispatching the SAME steps from a checkpoint
        must not keep /healthz green: only an ADVANCING fence (a new
        high-water step) refreshes the liveness probe."""
        rec = telemetry.configure(str(tmp_path / "t.jsonl"))
        server = telemetry.MetricsServer(0, recorder=rec,
                                         stale_after_s=0.4)
        port = server.start()
        try:
            rec.span_event("step_dispatch", 0.004, step=5)
            status, _ = _scrape(port, "/healthz")
            assert status == 200
            # keep re-dispatching step 5 (and older) past the fence age
            deadline = time.monotonic() + 0.7
            while time.monotonic() < deadline:
                rec.span_event("step_dispatch", 0.004, step=5)
                rec.span_event("step_dispatch", 0.004, step=3)
                time.sleep(0.05)
            with pytest.raises(urllib.error.HTTPError) as err:
                _scrape(port, "/healthz")
            assert err.value.code == 503
            # a genuinely new step revives it
            rec.span_event("step_dispatch", 0.004, step=6)
            status, _ = _scrape(port, "/healthz")
            assert status == 200
        finally:
            server.stop()

    def test_bind_failure_never_raises_from_the_wiring(self, tmp_path,
                                                       capsys):
        """The train.py/serving entry path: a taken port returns None
        (stderr-noted) instead of killing the run — the live surface
        shares the recorder's never-take-the-run-down contract."""
        rec = telemetry.configure(str(tmp_path / "t.jsonl"))
        holder = telemetry.MetricsServer(0, recorder=None)
        port = holder.start()   # squat the port
        try:
            assert telemetry.start_metrics_server(port, rec) is None
            assert "could not bind" in capsys.readouterr().err
            assert rec._observers == []   # nothing half-attached
        finally:
            holder.stop()
            telemetry.stop_metrics_server()

    def test_observer_detaches_on_stop(self, tmp_path):
        rec = telemetry.configure(str(tmp_path / "t.jsonl"))
        server = telemetry.MetricsServer(0, recorder=rec)
        server.start()
        assert rec._observers
        server.stop()
        assert rec._observers == []
        rec.counter("after", 1)  # no observer left to call


# ---------------------------------------------------------------------------
# two-tier wire rows end to end (ISSUE 16)
# ---------------------------------------------------------------------------


class TestTwoTierWireEndToEnd:
    def test_dcn_tier_row_survives_stream_aggregate_and_metrics(
            self, tmp_path):
        """One ``int8_hier`` emission produces the two tier rows —
        (tier="ici", axis="data") and (tier="dcn", axis="slice") — and
        the SAME rows survive every hop with zero schema change: the
        per-rank JSONL stream, the fleet aggregate's (name, tier, axis)
        rollup summed across ranks, and the /metrics render as one more
        ``dpt_wire_bytes_total`` label value."""
        import numpy as np

        from distributed_pytorch_training_tpu.parallel.grad_sync import (
            emit_wire_accounting, wire_bytes_split_for_config,
        )

        params = {"w": np.zeros((4096,), np.float32),
                  "b": np.zeros((31,), np.float32)}
        # in train.py/bench the trainer injects `slices` from the mesh
        # (wire_accounting_inputs); here the test plays that role
        cfg = {"wire_dtype": "int8_hier", "slices": 2}
        expect = wire_bytes_split_for_config(params, cfg, 4)
        assert expect["dcn"] > 0 and expect["ici"] > expect["dcn"]

        paths, server, port = [], None, None
        try:
            for rank in (0, 1):
                p = tmp_path / f"telemetry_rank{rank}.jsonl"
                rec = telemetry.configure(str(p), gen=0, rank=rank)
                if rank == 0:
                    server = telemetry.MetricsServer(0, recorder=rec)
                    port = server.start()
                out = emit_wire_accounting(params, cfg, 4)
                assert out["wire_bytes_dcn"] == expect["dcn"]
                paths.append(p)
            # hop 1: the per-rank stream carries BOTH tier rows
            events, _bad = read_stream(str(paths[1]))
            rows = {(e["tier"], e["axis"]): e["value"] for e in events
                    if e.get("kind") == "counter"
                    and e.get("name") == "wire_bytes_per_replica"}
            assert rows == {("ici", "data"): expect["ici"],
                            ("dcn", "slice"): expect["dcn"]}
            # hop 2: the fleet rollup keys (name, tier, axis) and sums
            # across ranks — the dcn tier is just one more row
            agg = aggregate_streams(paths)
            wire = {(w["name"], w["tier"], w["axis"]): w["total"]
                    for w in agg["wire"]}
            assert wire[("wire_bytes_per_replica", "dcn", "slice")] \
                == 2 * expect["dcn"]
            assert wire[("wire_bytes_per_replica", "ici", "data")] \
                == 2 * expect["ici"]
            # hop 3: /metrics renders it (rank 0's server observed only
            # rank 0's emission — per-rank scoping holds)
            _, body = _scrape(port)
            assert ('dpt_wire_bytes_total{name="wire_bytes_per_replica"'
                    ',tier="dcn",axis="slice"} '
                    + format(float(expect["dcn"]), "g")) in body
            assert ('dpt_wire_bytes_total{name="wire_bytes_per_replica"'
                    ',tier="ici",axis="data"} '
                    + format(float(expect["ici"]), "g")) in body
        finally:
            if server is not None:
                server.stop()


# ---------------------------------------------------------------------------
# StreamFollower: tail -f and the fleet's live progress probe
# ---------------------------------------------------------------------------


class TestStreamFollower:
    def test_incremental_poll_and_partial_lines(self, tmp_path):
        p = tmp_path / "s.jsonl"
        follower = StreamFollower(p)
        assert follower.poll() == []      # not created yet: not an error
        with open(p, "w") as f:
            f.write(json.dumps({"kind": "counter", "name": "a",
                                "value": 1}) + "\n")
            f.write('{"kind": "counter", "name": "b"')   # torn mid-write
        evs = follower.poll()
        assert [e["name"] for e in evs] == ["a"]
        with open(p, "a") as f:
            f.write(', "value": 2}\n')                   # line completes
        assert [e["name"] for e in follower.poll()] == ["b"]

    def test_rotation_to_a_new_stream_file(self, tmp_path):
        p = tmp_path / "s.jsonl"
        p.write_text(json.dumps({"kind": "counter", "name": "old",
                                 "value": 1}) + "\n")
        follower = StreamFollower(p)
        assert [e["name"] for e in follower.poll()] == ["old"]
        # rotate: a NEW file replaces the old path (new inode)
        rotated = tmp_path / "rotated.jsonl"
        rotated.write_text(json.dumps({"kind": "counter", "name": "new",
                                       "value": 2}) + "\n")
        rotated.replace(p)
        assert [e["name"] for e in follower.poll()] == ["new"]

    def test_last_step_probe(self, tmp_path):
        p = _write_stream(tmp_path / "s.jsonl", 0, 0, anchor_ts=0.0,
                          steps=5)
        follower = StreamFollower(p)
        assert last_step_of(follower.poll()) == 4
        assert last_step_of([], prior=4) == 4

    def test_last_step_probe_is_generation_scoped(self, tmp_path):
        """On the shared appended stream, a previous generation's spans
        must not read as THIS child's progress."""
        p = _write_stream(tmp_path / "s.jsonl", 0, 0, anchor_ts=0.0,
                          steps=9)
        _write_stream(p, 1, 0, anchor_ts=50.0, steps=3, append=True)
        events = StreamFollower(p).poll()
        assert last_step_of(events, gen=1) == 2
        assert last_step_of(events, gen=0) == 8
        assert last_step_of(events, gen=2) == -1   # nothing of gen 2 yet

    def test_start_at_end_skips_the_backlog(self, tmp_path):
        """The fleet watch arms a follower on a file that already holds
        earlier generations: start_at_end skips the backlog (no O(N^2)
        re-parse per child) and still sees everything appended after."""
        p = _write_stream(tmp_path / "s.jsonl", 0, 0, anchor_ts=0.0,
                          steps=50)
        follower = StreamFollower(p, start_at_end=True)
        assert follower.poll() == []        # backlog skipped
        _write_stream(p, 1, 0, anchor_ts=9.0, steps=2, append=True)
        evs = follower.poll()
        assert evs and all(e.get("gen") == 1 for e in evs)

    def test_start_at_end_on_a_not_yet_created_file_skips_nothing(
            self, tmp_path):
        """The snapshot is taken at ARM time: a file created AFTERWARDS
        (a fresh fleet run — gen 0's own stream) has no backlog, and the
        child's first events are never discarded."""
        p = tmp_path / "later.jsonl"
        follower = StreamFollower(p, start_at_end=True)
        assert follower.poll() == []
        _write_stream(p, 0, 0, anchor_ts=0.0, steps=3)
        evs = follower.poll()
        assert last_step_of(evs, gen=0) == 2   # nothing was skipped

    def test_importing_telemetry_does_not_load_metrics_http(self):
        """metrics_http's zero-cost-when-off contract starts at import:
        the package (the training hot path, the jax-free CLI readers)
        resolves the live-surface names lazily, so the OFF path never
        pays the http.server import (subprocess: this process's
        sys.modules is already warm)."""
        import subprocess
        import sys as _sys
        src = (
            "import sys; sys.path.insert(0, " + repr(str(REPO)) + ")\n"
            "import distributed_pytorch_training_tpu.telemetry\n"
            "mod = 'distributed_pytorch_training_tpu.telemetry"
            ".metrics_http'\n"
            "assert mod not in sys.modules, 'eagerly imported'\n"
            "import distributed_pytorch_training_tpu.telemetry as t\n"
            "assert t.resolve_metrics_port(0) == 0\n"
            "assert mod in sys.modules  # first use loads it\n")
        r = subprocess.run([_sys.executable, "-c", src],
                           capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, r.stderr

    def test_tail_follow_cli_bounded(self, tmp_path, capsys):
        p = _write_stream(tmp_path / "s.jsonl", 0, 0, anchor_ts=0.0,
                          steps=3)
        rc = telemetry_main(["tail", str(p), "-n", "2", "-f",
                             "--poll-s", "0.05",
                             "--follow-timeout", "0.2"])
        assert rc == 0
        out = capsys.readouterr().out.strip().splitlines()
        assert len(out) == 2              # the backlog tail
        assert json.loads(out[-1])["kind"] == "counter"
