"""serving/ — token-granular continuous batching + paged int8 KV +
multi-replica router (ISSUE 17).

Pins, in order:
* `PagePool` allocator semantics: refcounts, prefix sharing, LRU
  eviction of retained prefix pages, admission-control failure (None,
  nothing leaked);
* SlotEngine greedy decode is BITWISE the solo full-context forward for
  mixed-length requests, with joins/leaves at token granularity
  (per-request ``max_new_tokens`` completing mid-batch);
* zero recompiles after warmup across >= 20 mixed-length admissions;
* sampling determinism: the emitted stream is a function of (request,
  seed) alone — slot assignment, join order, and batch company are
  invisible; ``temperature=0`` is bitwise greedy;
* the int8 paged pool cuts KV bytes >= 3x vs the dense fp32 baseline and
  quantizes deterministically (same request -> same tokens, twice);
* `slot_wait` / `router_dispatch` spans + the slot-occupancy / page-pool
  gauges are registered span names, emitted live, and bucketed by
  `telemetry summary` into the step-time split (not "unaccounted");
* the ``serving_paged`` contract + `paged-pool-donated` rule,
  mutation-tested per the checker's own standard;
* the fleet acceptance drill: 20+ mixed-length requests over 2
  router-fronted replicas on DISJOINT device slices, one replica killed
  with work in flight — every request completes (seed-pinned resubmit),
  zero recompiles on either engine, outputs bitwise the solo forwards;
* scheduler kill fails queued-but-unpulled requests too (no orphaned
  waiters), and the router unit semantics (least-depth, resubmit).
"""

import collections
import socket
import subprocess
import sys
import threading
import time
import urllib.error
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_training_tpu import telemetry
from distributed_pytorch_training_tpu.models.gpt2 import GPT2LMHead
from distributed_pytorch_training_tpu.parallel import MeshSpec, build_mesh
from distributed_pytorch_training_tpu.serving import batching
from distributed_pytorch_training_tpu.serving.batching import RequestQueue
from distributed_pytorch_training_tpu.serving.continuous import (
    ContinuousScheduler, SlotEngine, sample_tokens,
)
from distributed_pytorch_training_tpu.serving.paged import (
    PagedServeConfig, PagePool,
)
from distributed_pytorch_training_tpu.serving.router import (
    HttpReplica, InProcessReplica, ReplicaDead, Router, RouterRequest,
)

VOCAB = 97


def tiny_model(**kw):
    cfg = dict(vocab_size=VOCAB, hidden_dim=32, depth=2, num_heads=2,
               max_position=64)
    cfg.update(kw)
    return GPT2LMHead(**cfg)


@pytest.fixture(scope="module")
def tiny(mesh8):
    model = tiny_model()
    params = model.init(jax.random.PRNGKey(0), np.zeros((1, 8), np.int32),
                        train=False)["params"]
    return model, params


def paged_cfg(**kw):
    cfg = dict(buckets=(8, 16), rows=8, max_new_tokens=6, page_size=4)
    cfg.update(kw)
    return PagedServeConfig(**cfg)


@pytest.fixture(scope="module")
def slot_engine(mesh8, tiny):
    model, params = tiny
    eng = SlotEngine(model, mesh8, paged_cfg(), params)
    eng.warmup()
    return eng


def prompts(ns, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, VOCAB, n).astype(np.int32) for n in ns]


_REF_PAD = 32          # >= longest prompt (16) + max_new_tokens (6)
_ref_fwd_cache: dict = {}


def ref_greedy(model, params, prompt, n):
    """The solo reference: greedy continuation off the full-context eval
    forward (test_serving.py's bitwise anchor, extended to a token loop).
    The forward is jitted at ONE fixed padded length so every reference
    decode in the file shares a single compile — the model is causal, so
    trailing pad cannot reach position cur-1, and the emitted argmax
    stream is identical to the per-length eager forward's (the float
    logits differ only by ~1e-7 fusion-order noise, which the pin — the
    TOKEN stream — does not see)."""
    fwd = _ref_fwd_cache.get(id(model))
    if fwd is None:
        fwd = jax.jit(lambda p, ids: model.apply({"params": p}, ids,
                                                 train=False))
        _ref_fwd_cache[id(model)] = fwd
    ids = np.zeros((1, _REF_PAD), np.int32)
    ids[0, :len(prompt)] = prompt
    cur = len(prompt)
    out = []
    for _ in range(n):
        logits = fwd(params, jnp.asarray(ids))
        nxt = int(jnp.argmax(logits[0, cur - 1]))
        out.append(nxt)
        ids[0, cur] = nxt
        cur += 1
    return np.asarray(out, np.int32)


def serve_all(engine, specs, timeout=300.0):
    """Reset the engine, push every spec through a fresh scheduler, drain,
    and return the per-request Results in submission order. ``specs`` are
    (tokens, kw) pairs for RequestQueue.submit."""
    engine.reset_state()
    q = RequestQueue(engine.config.buckets)
    sched = ContinuousScheduler(engine, q)
    reqs = [q.submit(toks, **kw) for toks, kw in specs]
    sched.drain()
    return [r.result(timeout=timeout) for r in reqs]


# ---------------------------------------------------------------------------
# PagePool: the host-side allocator
# ---------------------------------------------------------------------------


class TestPagePool:
    def test_scratch_page_never_leased(self):
        pool = PagePool(9, 4, 4, prefix_sharing=False)
        lease = pool.alloc(list(range(6)), 8)
        assert lease is not None and lease.n_pages == 2
        assert 0 not in lease.pages[:lease.n_pages]
        # unused table entries point at scratch page 0
        assert all(p == 0 for p in lease.pages[lease.n_pages:])

    def test_release_returns_pages(self):
        pool = PagePool(9, 4, 4, prefix_sharing=False)
        free0 = pool.free_pages()
        lease = pool.alloc(list(range(6)), 8)
        assert pool.free_pages() == free0 - 2
        pool.release(lease)
        assert pool.free_pages() == free0

    def test_prefix_sharing_maps_same_pages(self):
        pool = PagePool(17, 4, 4)
        toks = list(range(11))          # pages 0..1 fully covered
        a = pool.alloc(toks, 13)
        b = pool.alloc(toks, 13)
        assert a is not None and b is not None
        # the fully-covered prompt pages are the SAME physical pages
        np.testing.assert_array_equal(a.pages[:2], b.pages[:2])
        # the partial tail page is private to each lease
        assert a.pages[2] != b.pages[2]
        assert b.shared == list(a.pages[:2]) and pool.prefix_hits == 2

    def test_divergent_prompts_do_not_share(self):
        pool = PagePool(17, 4, 4)
        a = pool.alloc(list(range(8)), 8)
        b = pool.alloc(list(range(1, 9)), 8)
        assert set(map(int, a.pages[:2])).isdisjoint(
            set(map(int, b.pages[:2])))

    def test_lru_eviction_of_retained_prefix(self):
        # 4 physical pages (1 scratch + 3): a released prefix page parks
        # retained; exhausting the free list evicts it (oldest first)
        pool = PagePool(4, 4, 3)
        a = pool.alloc(list(range(4)), 4)      # 1 fully-covered page
        pool.release(a)
        assert pool.stats()["retained"] == 1
        b = pool.alloc(list(range(100, 112)), 12)   # needs all 3 pages
        assert b is not None and pool.evictions == 1
        assert pool.stats()["retained"] == 0

    def test_alloc_failure_leaks_nothing(self):
        pool = PagePool(4, 4, 8, prefix_sharing=False)
        free0 = pool.free_pages()
        assert pool.alloc(list(range(4)), 17) is None   # needs 5 > 3 pages
        assert pool.free_pages() == free0

    def test_dry_free_list_never_duplicates_matched_prefix(self):
        # free list dry + the matched prefix page parked retained at
        # refcount 0: alloc must claim the match at match time, not
        # evict it in the fresh-page loop and re-lease it — one physical
        # page at two logical offsets would let the prefill scatter
        # corrupt the shared prefix
        pool = PagePool(3, 4, 2)               # scratch + pages {1, 2}
        a = pool.alloc(list(range(4)), 4)      # 1 fully-covered page
        pool.release(a)                        # -> retained, refcount 0
        b = pool.alloc(list(range(100, 104)), 4)   # drains the free list
        assert b is not None
        stats0 = pool.stats()
        # shared hit on the retained page + 1 fresh page nothing can
        # supply: admission control (None), NOT a duplicated lease
        c = pool.alloc(list(range(4)), 8)
        assert c is None
        assert pool.stats() == stats0          # rollback re-parked it
        pool.release(b)                        # room opens up
        d = pool.alloc(list(range(4)), 8)
        assert d is not None
        pages = list(map(int, d.pages[:d.n_pages]))
        assert len(set(pages)) == len(pages)   # all distinct
        assert d.shared and 0 not in pages

    def test_config_validation_and_floor(self):
        with pytest.raises(ValueError, match="kv_dtype"):
            paged_cfg(kv_dtype="fp8")
        with pytest.raises(ValueError, match="page_size"):
            paged_cfg(page_size=0)
        cfg = paged_cfg()
        assert cfg.cache_len == 16 + 6
        assert cfg.pages_per_slot == 6           # ceil(22 / 4)
        assert cfg.total_pages == 8 * 6 + 1      # fail-safe floor + scratch


# ---------------------------------------------------------------------------
# SlotEngine: greedy bitwise parity + the zero-recompile census
# ---------------------------------------------------------------------------


class TestSlotEngineGreedy:
    def test_mixed_lengths_match_solo_forward_bitwise(self, slot_engine,
                                                      tiny):
        model, params = tiny
        seqs = prompts((3, 8, 11, 16, 5, 13), seed=1)
        res = serve_all(slot_engine,
                        [(s, dict(temperature=0.0)) for s in seqs])
        for i, (s, r) in enumerate(zip(seqs, res)):
            np.testing.assert_array_equal(
                r.tokens, ref_greedy(model, params, s, 6),
                err_msg=f"request {i} (len {len(s)})")

    def test_token_granular_join_leave(self, slot_engine, tiny):
        """Per-request budgets: rows leave the RUNNING batch the moment
        their own want is met (batch-mates keep decoding), and each
        stream is still the bitwise solo greedy prefix."""
        model, params = tiny
        seqs = prompts((4, 9, 6, 12, 7), seed=2)
        wants = [1, 6, 3, 5, 2]
        res = serve_all(slot_engine,
                        [(s, dict(temperature=0.0, max_new_tokens=w))
                         for s, w in zip(seqs, wants)])
        for s, w, r in zip(seqs, wants, res):
            assert r.tokens.shape == (w,)
            np.testing.assert_array_equal(
                r.tokens, ref_greedy(model, params, s, w))

    def test_zero_recompiles_after_warmup(self, slot_engine):
        rng = np.random.RandomState(5)
        before = slot_engine.compiles
        specs = [(rng.randint(0, VOCAB, int(rng.randint(1, 17)))
                  .astype(np.int32),
                  dict(temperature=0.0,
                       max_new_tokens=int(rng.randint(1, 7))))
                 for _ in range(22)]
        res = serve_all(slot_engine, specs)
        assert len(res) == 22 and all(r.tokens.size for r in res)
        assert slot_engine.compiles == before, \
            "an admission or decode step recompiled after warmup"

    def test_last_logits_match_eval_forward(self, slot_engine, tiny):
        """The compiled prefill's last-prompt logits agree with the eval
        forward to fusion-order noise (~1e-7 — the compiled (1, bucket)
        program fuses differently than the solo-shaped forward), and the
        emitted token IS their argmax — the bitwise pin lives on the
        token stream, not the float intermediates."""
        model, params = tiny
        (s,) = prompts((9,), seed=3)
        (r,) = serve_all(slot_engine, [(s, dict(temperature=0.0))])
        solo = np.asarray(
            model.apply({"params": params}, s[None],
                        train=False))[0, len(s) - 1]
        np.testing.assert_allclose(r.last_logits, solo, rtol=1e-5,
                                   atol=1e-6)
        assert int(r.tokens[0]) == int(np.argmax(r.last_logits))
        assert int(r.tokens[0]) == int(np.argmax(solo))


# ---------------------------------------------------------------------------
# Sampling determinism (the RNG-threading satellite)
# ---------------------------------------------------------------------------


class TestSamplingDeterminism:
    def test_temperature_zero_is_argmax(self):
        rng = np.random.RandomState(0)
        logits = jnp.asarray(rng.randn(5, VOCAB), jnp.float32)
        keys = jnp.stack([jax.random.PRNGKey(i) for i in range(5)])
        toks = sample_tokens(logits, keys, jnp.zeros(5), jnp.ones(5))
        np.testing.assert_array_equal(np.asarray(toks),
                                      np.argmax(np.asarray(logits), -1))

    def test_stream_ignores_slots_join_order_and_company(self, slot_engine,
                                                         tiny):
        """Same (prompt, seed, knobs) -> identical tokens whether the
        request runs alone, joins last behind one crowd, or first ahead
        of a different one — slot index and batch-mates are invisible."""
        (target,) = prompts((7,), seed=10)
        t_kw = dict(temperature=0.8, top_p=0.9, seed=1234,
                    max_new_tokens=6)
        decoys_a = [(s, dict(temperature=1.0, seed=50 + i,
                             max_new_tokens=3 + i % 4))
                    for i, s in enumerate(prompts((5, 12, 3, 9, 15, 6, 4),
                                                  seed=11))]
        decoys_b = [(s, dict(temperature=0.0, max_new_tokens=2 + i % 5))
                    for i, s in enumerate(prompts((14, 2, 8, 10), seed=12))]
        alone = serve_all(slot_engine, [(target, t_kw)])[0]
        last = serve_all(slot_engine, decoys_a + [(target, t_kw)])[-1]
        first = serve_all(slot_engine, [(target, t_kw)] + decoys_b)[0]
        np.testing.assert_array_equal(alone.tokens, last.tokens)
        np.testing.assert_array_equal(alone.tokens, first.tokens)

    def test_distinct_seeds_diverge(self, slot_engine):
        (s,) = prompts((8,), seed=13)
        kw = dict(temperature=1.0, top_p=1.0, max_new_tokens=6)
        a, b = serve_all(slot_engine, [(s, dict(seed=1, **kw)),
                                       (s, dict(seed=2, **kw))])
        assert not np.array_equal(a.tokens, b.tokens)


# ---------------------------------------------------------------------------
# int8 pages: the HBM cut + deterministic quantization
# ---------------------------------------------------------------------------


class TestInt8Pages:
    @pytest.fixture(scope="class")
    def int8_engine(self, mesh8):
        # head_dim 32 (the smallest real-model head width — gpt2 heads
        # are 64): the per-(row, head) fp32 scale amortizes over the head
        # dim, so the >= 3x cut needs real head widths; the depth-2
        # hidden-32 toy's head_dim 16 pays 25% scale overhead and lands
        # at ~2.9x, which is the honest accounting, not a miss
        model = tiny_model(hidden_dim=64)
        params = model.init(jax.random.PRNGKey(0),
                            np.zeros((1, 8), np.int32),
                            train=False)["params"]
        # one bucket: these tests pin bytes + determinism, not bucket
        # routing (TestSlotEngineGreedy owns that), and each extra
        # bucket is a whole extra prefill compile at hidden 64
        eng = SlotEngine(model, mesh8,
                         paged_cfg(buckets=(16,), kv_dtype="int8"), params)
        eng.warmup()
        return eng

    def test_byte_ratio_at_least_3x(self, int8_engine):
        ratio = (int8_engine.dense_baseline_bytes()
                 / int8_engine.paged_bytes())
        assert ratio >= 3.0, f"int8 paged/dense byte ratio {ratio:.2f} < 3"

    def test_quantization_is_deterministic(self, int8_engine):
        """The wire-codec grid story: serving the same requests twice
        (fresh pool each time) emits identical tokens — the int8
        perturbation is a deterministic function of the values, so every
        replica agrees (the router's resubmit-invisibility premise)."""
        seqs = prompts((6, 11, 4), seed=14)
        specs = [(s, dict(temperature=0.0)) for s in seqs]
        first = serve_all(int8_engine, specs)
        second = serve_all(int8_engine, specs)
        for a, b in zip(first, second):
            np.testing.assert_array_equal(a.tokens, b.tokens)
            np.testing.assert_array_equal(a.last_logits, b.last_logits)


class TestFusedPagedScatter:
    """ISSUE 20 satellite: the int8 page write path rides the PR 6 fused
    Pallas quantize kernels — every paged scatter (row, window, prefill)
    threads the codec's ``fused`` tri-state down to
    ``grad_sync._quantize_int8_rows``. On CPU the kernel runs in Pallas
    interpreter mode, and the PR 6 exactness model says the pool BYTES
    cannot depend on the flag: codes AND scales bitwise identical, fused
    vs the XLA-composed reference."""

    L, PAGES, PS, H, D = 2, 5, 4, 2, 8

    def _pool(self):
        from distributed_pytorch_training_tpu.models.layers import (
            init_paged_kv,
        )

        return init_paged_kv(self.L, self.PAGES, self.PS, self.H, self.D,
                             quantized=True)

    def _rand(self, shape, seed):
        return jnp.asarray(np.random.RandomState(seed)
                           .randn(*shape).astype(np.float32))

    def _assert_pools_bitwise(self, a, b):
        for leaf in ("k", "v", "k_scale", "v_scale"):
            np.testing.assert_array_equal(
                np.asarray(getattr(a, leaf)), np.asarray(getattr(b, leaf)),
                err_msg=f"paged pool leaf {leaf} depends on the fused flag")

    def test_row_scatter_fused_is_bitwise(self):
        from distributed_pytorch_training_tpu.models.layers import (
            scatter_paged_rows,
        )

        table = jnp.array([[1, 2], [3, 4], [2, 1]], jnp.int32)
        positions = jnp.array([0, 5, 3], jnp.int32)
        active = jnp.array([True, True, False])
        k = self._rand((self.L, 3, self.H, self.D), seed=0)
        v = self._rand((self.L, 3, self.H, self.D), seed=1)
        out = {f: scatter_paged_rows(self._pool(), table, positions, k, v,
                                     active, fused=f)
               for f in (False, True)}
        self._assert_pools_bitwise(out[False], out[True])
        assert np.asarray(out[True].k).any()  # the write actually landed

    def test_window_scatter_fused_is_bitwise(self):
        from distributed_pytorch_training_tpu.models.layers import (
            scatter_paged_window,
        )

        table = jnp.array([[1, 2], [3, 4]], jnp.int32)
        positions = jnp.array([[0, 1, 2], [4, 5, 6]], jnp.int32)
        active = jnp.array([[True, True, False], [True, True, True]])
        k = self._rand((self.L, 2, 3, self.H, self.D), seed=2)
        v = self._rand((self.L, 2, 3, self.H, self.D), seed=3)
        out = {f: scatter_paged_window(self._pool(), table, positions, k,
                                       v, active, fused=f)
               for f in (False, True)}
        self._assert_pools_bitwise(out[False], out[True])
        assert np.asarray(out[True].k).any()

    def test_prefill_scatter_fused_is_bitwise(self):
        from distributed_pytorch_training_tpu.models.layers import (
            scatter_paged_prefill,
        )

        page_row = jnp.array([1, 3], jnp.int32)
        k = self._rand((self.L, 2 * self.PS, self.H, self.D), seed=4)
        v = self._rand((self.L, 2 * self.PS, self.H, self.D), seed=5)
        length = jnp.int32(6)  # bucket padding past 6 must be dropped
        out = {f: scatter_paged_prefill(self._pool(), page_row, k, v,
                                        length, fused=f)
               for f in (False, True)}
        self._assert_pools_bitwise(out[False], out[True])
        assert np.asarray(out[True].k).any()


# ---------------------------------------------------------------------------
# Telemetry: registered spans, live gauges, summary bucketing
# ---------------------------------------------------------------------------


class TestServingTelemetry:
    def test_span_names_registered(self):
        from distributed_pytorch_training_tpu.telemetry.recorder import (
            REGISTERED_SPAN_NAMES, SERVING_SPAN_NAMES,
        )

        assert {"slot_wait", "router_dispatch"} <= set(SERVING_SPAN_NAMES)
        assert {"slot_wait", "router_dispatch"} <= set(
            REGISTERED_SPAN_NAMES)

    def test_spans_and_gauges_emitted_and_bucketed(self, slot_engine):
        """A routed serve emits slot_wait + router_dispatch spans and the
        occupancy/page-pool gauges; `telemetry summary` folds the spans
        into the step-time split instead of "unaccounted"."""
        from distributed_pytorch_training_tpu.telemetry.__main__ import (
            summarize,
        )

        slot_engine.reset_state()
        rec = telemetry.configure()          # ring-only stream
        try:
            replica = InProcessReplica("r0", slot_engine)
            router = Router([replica])
            reqs = [router.submit(s, temperature=0.0)
                    for s in prompts((5, 9, 12), seed=15)]
            for r in reqs:
                r.result(timeout=120.0)
            replica.stop()
            events = rec.tail(10_000)
        finally:
            telemetry.reset()
        names = {e["name"] for e in events if e["kind"] == "span"}
        assert {"slot_wait", "router_dispatch", "prefill"} <= names
        gauges = {e["name"] for e in events if e["kind"] == "gauge"}
        assert {"serving_slot_occupancy", "serving_page_pool_free",
                "serving_queue_depth"} <= gauges
        summary = summarize(events)
        assert "slot_wait" in summary["spans"]
        assert "router_dispatch" in summary["spans"]
        # the split accounts the serving phases by name (a typo'd name
        # would vanish into "unaccounted"); synthetic durations keep the
        # assertion robust to microsecond real spans rounding to 0
        synth = summarize([
            {"kind": "span", "name": n, "dur_ms": 5.0}
            for n in ("slot_wait", "router_dispatch")])
        assert set(synth["step_split_pct"]) == {"slot_wait",
                                                "router_dispatch"}


# ---------------------------------------------------------------------------
# The serving_paged contract + paged-pool-donated rule (mutation-tested)
# ---------------------------------------------------------------------------


class TestPagedContract:
    def test_contract_passes_on_mesh(self, mesh8):
        from distributed_pytorch_training_tpu.analysis.contracts import (
            get_contract,
        )
        from distributed_pytorch_training_tpu.analysis.hlo_rules import (
            check_artifacts, evaluate_contract,
        )

        contract = get_contract("serving_paged")
        # the matrix pins the int8 arm — the most droppable leaves
        assert contract.config.get("paged_kv_dtype") == "int8"
        artifacts = evaluate_contract(contract, mesh=mesh8)
        # layer-stacked pool: 4 int8 leaves (codes + scales), NOT x depth
        assert artifacts.config["paged_cache_leaves"] == 4
        findings = check_artifacts(artifacts)
        assert findings == [], [str(f) for f in findings]

    def test_live_engine_artifacts_pass(self, slot_engine):
        from distributed_pytorch_training_tpu.analysis.hlo_rules import (
            check_artifacts, paged_serving_artifacts,
        )

        artifacts = paged_serving_artifacts(slot_engine)
        assert artifacts.config["paged_cache_leaves"] == 2  # fp32 k/v
        assert check_artifacts(artifacts) == []

    def test_mutation_missing_alias_entries_flag(self):
        from distributed_pytorch_training_tpu.analysis.hlo_rules import (
            StepArtifacts, check_artifacts,
        )

        partial = StepArtifacts(
            name="mut", optimized_text=(
                "HloModule paged, input_output_alias={ {0}: (1, {}, "
                "may-alias) }, entry_computation_layout={()}"),
            config={"serving_paged": True, "donate_state": True,
                    "paged_cache_leaves": 4})
        found = check_artifacts(partial, rules=["paged-pool-donated"])
        assert len(found) == 1 and "1 of the >= 4" in found[0].message
        absent = StepArtifacts(
            name="mut2", optimized_text="HloModule paged",
            config={"serving_paged": True, "donate_state": True,
                    "paged_cache_leaves": 2})
        assert check_artifacts(absent, rules=["paged-pool-donated"])
        train = StepArtifacts(name="t", optimized_text="HloModule x",
                              config={"donate_state": False})
        assert check_artifacts(train, rules=["paged-pool-donated"]) == []

    def test_mutation_dropped_leaf_flags(self, slot_engine):
        """Raising the census above the real table simulates one pool
        leaf falling out of the alias set — the rule must fire on the
        REAL lowering, not only on synthetic text."""
        import dataclasses as dc

        from distributed_pytorch_training_tpu.analysis.hlo_rules import (
            check_artifacts, paged_serving_artifacts,
        )

        artifacts = paged_serving_artifacts(slot_engine)
        poisoned = dc.replace(
            artifacts, config={**artifacts.config,
                               "paged_cache_leaves":
                               artifacts.config["paged_cache_leaves"]
                               + 100})
        found = check_artifacts(poisoned, rules=["paged-pool-donated"])
        assert len(found) == 1


# ---------------------------------------------------------------------------
# Router unit semantics (no devices)
# ---------------------------------------------------------------------------


class _StubPending:
    def __init__(self, replica, fail_first=False):
        self.replica = replica
        self.fail = fail_first

    def result(self, timeout=None):
        if self.fail or self.replica.dead:
            raise ReplicaDead(f"replica {self.replica.name} died")
        from distributed_pytorch_training_tpu.serving.batching import Result

        return Result(tokens=np.zeros(1, np.int32),
                      last_logits=np.zeros(VOCAB, np.float32))


class _StubReplica:
    def __init__(self, name, depth=0):
        self.name = name
        self.depth = depth
        self.dead = False
        self.submits = []

    def healthy(self):
        return not self.dead

    def queue_depth(self):
        return self.depth

    def submit(self, tokens, **kw):
        if self.dead:
            raise ReplicaDead(f"replica {self.name} is down")
        self.submits.append(kw)
        return _StubPending(self)


class TestRouterUnits:
    def test_least_depth_wins(self):
        a, b = _StubReplica("a", depth=5), _StubReplica("b", depth=1)
        router = Router([a, b])
        for _ in range(3):
            router.submit(np.ones(4, np.int32)).result(timeout=1.0)
        assert len(b.submits) == 3 and not a.submits

    def test_seed_pinned_at_route_time_and_survives_resubmit(self):
        a, b = _StubReplica("a"), _StubReplica("b")
        router = Router([a, b])
        req = router.submit(np.ones(4, np.int32))
        seed = req.kw["seed"]
        assert seed is not None
        first = req.replica_name
        req._inner.fail = True            # the dispatched copy dies
        router.replicas[first].dead = True
        req.result(timeout=1.0)           # resubmits to the survivor
        assert req.replica_deaths == 1 and req.replica_name != first
        survivor = router.replicas[req.replica_name]
        assert survivor.submits[-1]["seed"] == seed

    def test_distinct_requests_get_distinct_seeds(self):
        router = Router([_StubReplica("a")])
        r1 = router.submit(np.ones(4, np.int32))
        r2 = router.submit(np.ones(4, np.int32))
        assert r1.kw["seed"] != r2.kw["seed"]

    def test_no_healthy_replicas_raises(self):
        a = _StubReplica("a")
        a.dead = True
        router = Router([a])
        with pytest.raises(ReplicaDead, match="no healthy"):
            router.submit(np.ones(4, np.int32))

    def test_duplicate_names_rejected(self):
        with pytest.raises(ValueError, match="unique"):
            Router([_StubReplica("a"), _StubReplica("a")])

    def test_slow_replica_times_out_without_resubmit(self):
        """A healthy-but-slow replica raises TimeoutError from result():
        the router must surface it, not declare the replica dead and
        stack a duplicate in-flight copy of the request on it."""
        class _SlowPending:
            def result(self, timeout=None):
                raise TimeoutError("still pending")

        class _SlowReplica(_StubReplica):
            def submit(self, tokens, **kw):
                self.submits.append(kw)
                return _SlowPending()

        a = _SlowReplica("a")
        req = Router([a]).submit(np.ones(4, np.int32))
        with pytest.raises(TimeoutError):
            req.result(timeout=0.2)
        assert req.replica_deaths == 0
        assert len(a.submits) == 1     # exactly one in-flight copy

    def test_replica_death_loop_respects_deadline(self):
        """Every dispatch dies instantly while the replica still reports
        healthy (the pathological spin): the caller's deadline must
        surface as TimeoutError, never an unbounded resubmit loop."""
        class _DyingPending:
            def __init__(self, name):
                self.name = name

            def result(self, timeout=None):
                time.sleep(0.001)
                raise ReplicaDead(f"replica {self.name} died")

        class _DyingReplica(_StubReplica):
            def submit(self, tokens, **kw):
                self.submits.append(kw)
                return _DyingPending(self.name)

        router = Router([_DyingReplica("a"), _DyingReplica("b")])
        req = router.submit(np.ones(4, np.int32))
        t0 = time.perf_counter()
        with pytest.raises(TimeoutError, match="replica deaths"):
            req.result(timeout=0.2)
        assert time.perf_counter() - t0 < 5.0
        assert req.replica_deaths >= 1

    def test_http_pending_timeout_is_not_a_death(self, monkeypatch):
        """Socket timeouts (bare or URLError-wrapped) surface as
        TimeoutError and leave the replica healthy; a refused connection
        is ReplicaDead and marks it down."""
        import urllib.request as _ur

        replica = HttpReplica("h", port=1)

        for exc in (socket.timeout("timed out"),
                    urllib.error.URLError(socket.timeout("timed out"))):
            def _raise(*a, _exc=exc, **kw):
                raise _exc
            monkeypatch.setattr(_ur, "urlopen", _raise)
            with pytest.raises(TimeoutError):
                replica.submit(np.ones(3, np.int32)).result(timeout=0.1)
            assert replica.healthy()   # slow is not dead

        def _refuse(*a, **kw):
            raise ConnectionRefusedError("refused")
        monkeypatch.setattr(_ur, "urlopen", _refuse)
        with pytest.raises(ReplicaDead):
            replica.submit(np.ones(3, np.int32)).result(timeout=0.1)
        assert not replica.healthy()


# ---------------------------------------------------------------------------
# Scheduler kill: nothing hangs
# ---------------------------------------------------------------------------


class TestSchedulerKill:
    def test_kill_fails_queued_pending_and_running(self, slot_engine):
        """An injected death resolves EVERY accepted request — including
        the ones still parked in the queue (an abandoned queue entry
        would hang its waiter forever; the router needs the error to
        resubmit)."""
        slot_engine.reset_state()
        q = RequestQueue(slot_engine.config.buckets)
        sched = ContinuousScheduler(slot_engine, q)
        reqs = [q.submit(s, temperature=0.0)
                for s in prompts((4, 7, 10), seed=16)]
        failed = sched.kill()
        assert len(failed) == 3
        for r in reqs:
            with pytest.raises(RuntimeError, match="died"):
                r.result(timeout=5.0)
        # the queue refuses new work after the death
        with pytest.raises(RuntimeError):
            q.submit(np.ones(4, np.int32))

    def test_kill_mid_step_resolves_each_request_exactly_once(
            self, monkeypatch):
        """kill() runs on the CALLER's thread while the worker is inside
        step(): it must wait for the step boundary — no 'dict changed
        size' crash iterating running/pending, and no request resolved
        twice (set_result by the completing step AND set_error by the
        kill). A stub engine with a slow decode step widens the race
        window; the scheduler lock is what keeps this green."""
        cfg = paged_cfg()

        class _StubEngine:
            config = cfg
            _control = {"tok": np.zeros(cfg.rows, np.int32)}

            def set_page_row(self, slot, row):
                pass

            def admit(self, slot, tokens, want, temperature, top_p, seed):
                return cfg.buckets[-1]

            def decode_step(self):
                time.sleep(0.002)

            def fetch_slot(self, slot):
                return (np.zeros(cfg.max_new_tokens, np.int32),
                        np.zeros(VOCAB, np.float32))

        resolutions = collections.Counter()
        count_lock = threading.Lock()
        orig_result = batching.Request.set_result
        orig_error = batching.Request.set_error

        def counting_result(self, res):
            with count_lock:
                resolutions[self.id] += 1
            orig_result(self, res)

        def counting_error(self, err):
            with count_lock:
                resolutions[self.id] += 1
            orig_error(self, err)

        monkeypatch.setattr(batching.Request, "set_result",
                            counting_result)
        monkeypatch.setattr(batching.Request, "set_error", counting_error)

        q = RequestQueue(cfg.buckets)
        sched = ContinuousScheduler(_StubEngine(), q)
        stop = threading.Event()
        worker_err: list = []

        def run():
            try:
                sched.run(stop)
            except BaseException as e:  # noqa: BLE001 - the race crash
                worker_err.append(e)

        t = threading.Thread(target=run, daemon=True)
        t.start()
        reqs = [q.submit(s) for s in prompts([4] * 30, seed=23)]
        time.sleep(0.02)               # land the kill with work in flight
        sched.kill()
        t.join(timeout=30.0)
        assert not t.is_alive()
        assert not worker_err, f"worker crashed: {worker_err}"
        served = failed = 0            # everything resolves, nothing hangs
        for r in reqs:
            try:
                r.result(timeout=5.0)
                served += 1
            except RuntimeError:       # the kill's error (ReplicaDead kin)
                failed += 1
        assert served + failed == len(reqs) and failed > 0
        assert len(resolutions) == len(reqs)
        assert set(resolutions.values()) == {1}, (
            f"double-resolved requests: "
            f"{[i for i, n in resolutions.items() if n > 1]}")


# ---------------------------------------------------------------------------
# The fleet acceptance drill: 2 replicas, 1 death, all bitwise
# ---------------------------------------------------------------------------


class TestFleetAcceptance:
    @pytest.fixture(scope="class")
    def fleet_engines(self, devices, tiny):
        """Two SlotEngines on DISJOINT 4-device slices — the fleet
        topology (replicas do not share chips), and a hard in-process
        requirement: the row-sharded decode carries collectives, and two
        scheduler threads dispatching collective programs over
        OVERLAPPING device sets deadlock the CPU rendezvous."""
        model, params = tiny
        engines = []
        for i in range(2):
            mesh = build_mesh(MeshSpec(data=4),
                              devices=devices[i * 4:(i + 1) * 4])
            eng = SlotEngine(model, mesh, paged_cfg(), params)
            eng.warmup()
            engines.append(eng)
        return engines

    def test_fleet_kill_all_complete_bitwise(self, fleet_engines, tiny):
        model, params = tiny
        eng_a, eng_b = fleet_engines
        warm = (eng_a.compiles, eng_b.compiles)
        ra = InProcessReplica("r0", eng_a)
        rb = InProcessReplica("r1", eng_b)
        router = Router([ra, rb])
        rng = np.random.RandomState(7)
        seqs = [rng.randint(0, VOCAB, int(rng.randint(1, 17)))
                .astype(np.int32) for _ in range(22)]
        reqs = [router.submit(s, temperature=0.0, max_new_tokens=6)
                for s in seqs]
        # the death must land with work IN FLIGHT on r0: submission is
        # instant and service is not, so depth > 0 immediately
        deadline = time.perf_counter() + 30.0
        while time.perf_counter() < deadline and ra.queue_depth() == 0:
            time.sleep(0.001)
        assert ra.queue_depth() > 0, "r0 never held work to kill"
        failed = ra.kill()
        assert failed, "the kill found nothing in flight"
        results = [r.result(timeout=300.0) for r in reqs]

        assert len(results) == 22
        assert sum(r.replica_deaths for r in reqs) >= 1
        assert not ra.healthy() and rb.healthy()
        # zero recompiles on BOTH engines, through death and resubmission
        assert (eng_a.compiles, eng_b.compiles) == warm
        # every stream bitwise the solo full-context greedy forward —
        # resubmission is invisible in the output
        for i, (s, res) in enumerate(zip(seqs, results)):
            np.testing.assert_array_equal(
                res.tokens, ref_greedy(model, params, s, 6),
                err_msg=f"request {i} (len {len(s)}, "
                        f"deaths {reqs[i].replica_deaths})")
        router.stop()


# ---------------------------------------------------------------------------
# The CLI bench arm (slow: subprocess e2e)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_cli_bench_continuous_exits_zero(tmp_path):
    """`serving bench --continuous --mixed-want` runs the offered-load
    row end to end and exits 0 iff recompiles_after_warmup == 0 (the
    hard gate the fleet bench arms reuse)."""
    import os

    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    out = subprocess.run(
        [sys.executable, "-m",
         "distributed_pytorch_training_tpu.serving", "bench",
         "--continuous", "--mixed-want",
         "--model", "gpt2_124m",
         "--model-overrides", "hidden_dim=32,depth=2,num_heads=2",
         "--buckets", "8,16", "--rows", "8", "--max-new-tokens", "4",
         "--requests", "8", "--offered-load", "16",
         "--output-dir", str(tmp_path / "out")],
        env=env, cwd=str(Path(__file__).resolve().parent.parent),
        capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stdout + out.stderr
