"""Data pipeline tests: sampler shard semantics (mirroring DistributedSampler,
/root/reference/train_ddp.py:121-139), synthetic datasets, augmentation,
sharded loader."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_training_tpu.data import (
    CIFAR10_MEAN,
    CIFAR10_STD,
    ShardedLoader,
    ShardedSampler,
    get_dataset,
    normalize_images,
    random_crop_flip,
    synthetic_image_dataset,
)


class TestSampler:
    def test_shards_disjoint_and_exhaustive(self):
        # The DistributedSampler contract (ref :122-127): every sample seen
        # exactly once per epoch across ranks (ignoring padding).
        n, gb, procs = 103, 20, 4
        seen = []
        for p in range(procs):
            s = ShardedSampler(n=n, global_batch=gb, process_index=p,
                               process_count=procs, seed=7)
            idx, w = s.epoch_indices(epoch=0)
            assert idx.shape == (6, 5)  # ceil(103/20)=6 steps, 20/4=5 local
            seen.append(idx.ravel()[w.ravel() > 0])
        all_seen = np.concatenate(seen)
        assert sorted(all_seen) == list(range(n))

    def test_epoch_reshuffles_deterministically(self):
        s = ShardedSampler(n=50, global_batch=10, seed=3)
        a0, _ = s.epoch_indices(0)
        a0b, _ = s.epoch_indices(0)
        a1, _ = s.epoch_indices(1)
        np.testing.assert_array_equal(a0, a0b)  # set_epoch determinism (:185)
        assert not np.array_equal(a0, a1)

    def test_no_shuffle_is_sequential(self):
        s = ShardedSampler(n=20, global_batch=10, shuffle=False)
        idx, w = s.epoch_indices(0)
        np.testing.assert_array_equal(idx.ravel(), np.arange(20))
        assert w.min() == 1.0

    def test_drop_last_true(self):
        s = ShardedSampler(n=25, global_batch=10, drop_last=True)
        assert s.steps_per_epoch() == 2
        idx, w = s.epoch_indices(0)
        assert idx.shape == (2, 10) and w.min() == 1.0

    def test_padding_weights(self):
        # drop_last=False (ref :139): final batch padded, weights mark it.
        s = ShardedSampler(n=25, global_batch=10)
        idx, w = s.epoch_indices(0)
        assert idx.shape == (3, 10)
        assert w.sum() == 25.0
        assert (w[-1] == 0).sum() == 5

    def test_uneven_process_split_raises(self):
        with pytest.raises(ValueError):
            ShardedSampler(n=10, global_batch=10, process_count=3)


class TestDatasets:
    def test_synthetic_deterministic(self):
        a = synthetic_image_dataset(100, seed=1)
        b = synthetic_image_dataset(100, seed=1)
        np.testing.assert_array_equal(a.images, b.images)
        assert a.images.shape == (100, 32, 32, 3) and a.images.dtype == np.uint8

    def test_get_dataset_falls_back_to_synthetic(self, tmp_path):
        ds = get_dataset("cifar10", data_dir=str(tmp_path), train=True,
                         synthetic_size=64)
        assert ds.synthetic and len(ds) == 64 and ds.num_classes == 10

    def test_get_dataset_imagenet_synthetic(self):
        ds = get_dataset("imagenet", synthetic_size=8, train=False)
        assert ds.images.shape == (8, 224, 224, 3) and ds.num_classes == 1000

    def test_unknown_dataset_raises(self):
        with pytest.raises(ValueError):
            get_dataset("mnist")

    def test_cifar10_disk_roundtrip(self, tmp_path):
        # Write the standard pickle layout and read it back (ref :103-108).
        import pickle

        root = tmp_path / "cifar-10-batches-py"
        root.mkdir()
        rng = np.random.RandomState(0)
        for i in range(1, 6):
            data = rng.randint(0, 256, (20, 3072), dtype=np.int64)
            with open(root / f"data_batch_{i}", "wb") as f:
                pickle.dump({"data": data, "labels": rng.randint(0, 10, 20).tolist()}, f)
        ds = get_dataset("cifar10", data_dir=str(tmp_path), train=True)
        assert not ds.synthetic
        assert ds.images.shape == (100, 32, 32, 3)


class TestAugment:
    def test_normalize_matches_reference_formula(self):
        img = np.full((2, 4, 4, 3), 128, np.uint8)
        out = normalize_images(jnp.asarray(img), CIFAR10_MEAN, CIFAR10_STD)
        expect = (128 / 255.0 - np.asarray(CIFAR10_MEAN)) / np.asarray(CIFAR10_STD)
        np.testing.assert_allclose(np.asarray(out)[0, 0, 0], expect, rtol=1e-5)

    def test_crop_flip_shape_and_determinism(self):
        imgs = jnp.asarray(np.random.RandomState(0).randint(0, 256, (8, 32, 32, 3), dtype=np.uint8))
        key = jax.random.PRNGKey(0)
        a = random_crop_flip(imgs, key)
        b = random_crop_flip(imgs, key)
        assert a.shape == imgs.shape
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        c = random_crop_flip(imgs, jax.random.PRNGKey(1))
        assert not np.array_equal(np.asarray(a), np.asarray(c))

    def test_crop_flip_is_pure_selection_every_dtype(self):
        # The one-hot-matmul crop must be bit-exact pure selection: every
        # output pixel appears verbatim in the zero-padded input, including
        # dtypes wider than the bf16 selection pass can represent (uint16 /
        # int32 values > 256 route through the f32 HIGHEST pass).
        rs = np.random.RandomState(3)
        for dtype, hi in ((np.uint8, 256), (np.uint16, 60000),
                          (np.int32, 1 << 20), (np.float32, 1 << 20)):
            raw = rs.randint(0, hi, (4, 8, 8, 3)).astype(dtype)
            if dtype == np.float32:
                raw += rs.rand(*raw.shape).astype(np.float32)
            out = np.asarray(random_crop_flip(jnp.asarray(raw),
                                              jax.random.PRNGKey(5)))
            assert out.dtype == dtype
            allowed = set(raw.reshape(-1).tolist()) | {0}
            assert set(out.reshape(-1).tolist()) <= allowed, dtype

    def test_crop_content_preserved_without_padding_region(self):
        # zero padding: crop offsets can pull in zeros; flip only mirrors.
        imgs = jnp.ones((4, 8, 8, 3), jnp.float32)
        out = random_crop_flip(imgs, jax.random.PRNGKey(0), padding=0)
        np.testing.assert_array_equal(np.asarray(out), np.ones((4, 8, 8, 3)))


class TestLoader:
    def test_loader_batches_sharded(self, mesh8):
        ds = synthetic_image_dataset(100, seed=0)
        loader = ShardedLoader(ds, mesh8, per_device_batch=4, shuffle=True, seed=1)
        # global batch 32, ceil(100/32)=4 steps
        assert len(loader) == 4
        batches = list(loader.epoch(0))
        assert len(batches) == 4
        b = batches[0]
        assert b["image"].shape == (32, 32, 32, 3)
        assert len(b["image"].addressable_shards) == 8
        assert b["image"].addressable_shards[0].data.shape[0] == 4  # per-device batch
        total_weight = sum(float(b["weight"].sum()) for b in batches)
        assert total_weight == 100.0

    def test_loader_epoch_coverage(self, mesh8):
        ds = synthetic_image_dataset(64, seed=0)
        loader = ShardedLoader(ds, mesh8, per_device_batch=2, shuffle=True)
        seen = []
        for b in loader.epoch(3):
            w = np.asarray(b["weight"])
            labels = np.asarray(b["label"])[w > 0]
            seen.append(labels)
        assert len(np.concatenate(seen)) == 64

    def test_loader_producer_error_propagates(self, mesh8):
        ds = synthetic_image_dataset(32, seed=0)
        loader = ShardedLoader(ds, mesh8, per_device_batch=2, shuffle=False)
        loader.dataset.images = "not an array"  # force producer failure
        with pytest.raises(Exception):
            list(loader.epoch(0))


def test_sampler_pads_with_wrapped_real_samples():
    """Padding slots must repeat real (shuffled) indices, not index 0 — so
    BatchNorm batch statistics see real samples (DistributedSampler-style)."""
    s = ShardedSampler(n=25, global_batch=10, seed=0)
    idx, w = s.epoch_indices(0)
    flat_idx, flat_w = idx.ravel(), w.ravel()
    pad_idx = flat_idx[flat_w == 0]
    assert len(pad_idx) == 5
    # the padded ids are the head of the permutation (wrap-around), which for
    # a shuffled epoch is not all-zeros
    from distributed_pytorch_training_tpu import native

    order = native.permutation(s.seed + 0, 25)
    np.testing.assert_array_equal(pad_idx, order[:5])


def test_loader_early_abandon_does_not_leak_thread(mesh8):
    import threading

    ds = synthetic_image_dataset(256, seed=0)
    loader = ShardedLoader(ds, mesh8, per_device_batch=2, shuffle=False, prefetch=2)
    before = threading.active_count()
    it = loader.epoch(0)
    next(it)
    it.close()  # abandon mid-epoch
    import time as _t

    _t.sleep(0.5)
    assert threading.active_count() <= before + 1


class TestPythonFallbackLoader:
    """The pure-Python prefetch epoch (`ShardedLoader._python_epoch`) —
    what every host without the native library runs. The native path covers
    most CI environments, so these tests force the fallback explicitly."""

    @pytest.fixture(autouse=True)
    def _force_python_path(self, monkeypatch):
        from distributed_pytorch_training_tpu import native

        monkeypatch.setattr(native, "is_available", lambda: False)

    def test_padded_final_batch_weights(self, mesh8):
        # 100 samples, global batch 32: the 4th batch carries 4 real rows
        # and 28 zero-weight pads (drop_last=False, ref :139) — through the
        # QUEUE path, not just the sampler.
        ds = synthetic_image_dataset(100, seed=0)
        loader = ShardedLoader(ds, mesh8, per_device_batch=4, shuffle=False)
        batches = list(loader.epoch(0))
        assert len(batches) == 4
        w_last = np.asarray(batches[-1]["weight"])
        assert float(w_last.sum()) == 4.0
        assert set(np.unique(w_last)) == {0.0, 1.0}
        total = sum(float(np.asarray(b["weight"]).sum()) for b in batches)
        assert total == 100.0
        # the padded batch keeps the full static shape (one XLA program
        # serves every step)
        assert batches[-1]["image"].shape == (32, 32, 32, 3)

    def test_prefetch_thread_shuts_down_on_abandonment(self, mesh8):
        import threading
        import time as _t

        ds = synthetic_image_dataset(512, seed=0)
        loader = ShardedLoader(ds, mesh8, per_device_batch=2, shuffle=False,
                               prefetch=2)
        before = set(threading.enumerate())
        it = loader.epoch(0)
        next(it)  # producer thread is live and the queue is filling
        it.close()  # GeneratorExit -> stop.set() + drain + join
        deadline = _t.time() + 6.0
        while _t.time() < deadline:
            leaked = [t for t in threading.enumerate()
                      if t not in before and t.is_alive()]
            if not leaked:
                break
            _t.sleep(0.05)
        assert not leaked, f"producer thread(s) survived abandonment: {leaked}"

    def test_full_epoch_then_threads_retire(self, mesh8):
        import threading
        import time as _t

        ds = synthetic_image_dataset(64, seed=0)
        loader = ShardedLoader(ds, mesh8, per_device_batch=2, shuffle=True)
        before = set(threading.enumerate())
        seen = sum(float(np.asarray(b["weight"]).sum())
                   for b in loader.epoch(1))
        assert seen == 64.0
        deadline = _t.time() + 6.0
        while _t.time() < deadline:
            if not [t for t in threading.enumerate()
                    if t not in before and t.is_alive()]:
                break
            _t.sleep(0.05)
        assert not [t for t in threading.enumerate()
                    if t not in before and t.is_alive()]


class TestRealDataPipelines:
    """The r3 verdict's missing real-data paths (VERDICT r3 #3): packed
    ImageNet from disk (memmapped, no --synthetic) and tokenized LM corpora
    with a byte-level fallback."""

    def _write_packed(self, tmp_path, n=8, hw=16, num_classes=3):
        import json

        base = tmp_path / "imagenet"
        base.mkdir()
        rng = np.random.RandomState(0)
        for split, count in (("train", n), ("val", max(2, n // 2))):
            images = np.lib.format.open_memmap(
                base / f"{split}_images.npy", mode="w+", dtype=np.uint8,
                shape=(count, hw, hw, 3))
            images[:] = rng.randint(0, 256, images.shape)
            images.flush()
            np.save(base / f"{split}_labels.npy",
                    rng.randint(0, num_classes, count).astype(np.int64))
        (base / "classes.json").write_text(
            json.dumps([f"c{i}" for i in range(num_classes)]))
        return base

    def test_packed_imagenet_loads_as_real_data(self, tmp_path, mesh8):
        from distributed_pytorch_training_tpu.data.datasets import get_dataset
        from distributed_pytorch_training_tpu.data.loader import ShardedLoader

        self._write_packed(tmp_path)
        ds = get_dataset("imagenet", data_dir=str(tmp_path), train=True)
        assert not ds.synthetic
        assert ds.num_classes == 3
        # the memmap rides the normal loader path (native row gather)
        loader = ShardedLoader(ds, mesh8, per_device_batch=1, shuffle=True,
                               seed=0)
        batch = next(iter(loader.epoch(0)))
        assert batch["image"].shape == (8, 16, 16, 3)
        # absent files still fall back to synthetic, loudly
        ds2 = get_dataset("imagenet", data_dir=str(tmp_path / "nope"),
                          train=True, synthetic_size=16)
        assert ds2.synthetic

    def test_pack_tool_roundtrip_from_class_folders(self, tmp_path):
        from PIL import Image

        from distributed_pytorch_training_tpu.data.datasets import (
            load_imagenet,
        )
        from distributed_pytorch_training_tpu.data.pack import pack_images

        src = tmp_path / "raw"
        rng = np.random.RandomState(1)
        for cls in ("ant", "bee"):  # sorted order pins labels: ant=0, bee=1
            (src / cls).mkdir(parents=True)
            for i in range(3):
                h, w = rng.randint(20, 40, 2)
                Image.fromarray(
                    rng.randint(0, 256, (h, w, 3)).astype(np.uint8)
                ).save(src / cls / f"{i}.jpg")
        out = tmp_path / "packed" / "imagenet"
        pack_images(str(src), str(out), "train", size=16, log=lambda *_: None)

        ds = load_imagenet(str(tmp_path / "packed"), train=True)
        assert ds is not None and not ds.synthetic
        assert ds.images.shape == (6, 16, 16, 3)
        np.testing.assert_array_equal(np.asarray(ds.labels),
                                      [0, 0, 0, 1, 1, 1])
        assert ds.num_classes == 2

    def test_tokenize_bytes_fallback_end_to_end(self, tmp_path):
        from distributed_pytorch_training_tpu.data.text import (
            get_token_dataset,
        )
        from distributed_pytorch_training_tpu.data.tokenize import (
            tokenize_files,
        )

        text = "the quick brown fox jumps over the lazy dog. " * 50
        (tmp_path / "corpus.txt").write_text(text)
        tokenize_files([str(tmp_path / "corpus.txt")], "bytes",
                       str(tmp_path / "data"), "gpt2", val_fraction=0.2,
                       log=lambda *_: None)

        ds = get_token_dataset("gpt2", seq_len=32,
                               data_dir=str(tmp_path / "data"), train=True)
        assert not ds.synthetic
        assert ds.vocab_size == 50257  # byte ids are a subset of the vocab
        # token ids really are the UTF-8 bytes
        expect = np.frombuffer(text.encode(), np.uint8)
        got = np.asarray(ds.tokens).ravel()
        np.testing.assert_array_equal(got, expect[: len(got)])
        val = get_token_dataset("gpt2", seq_len=32,
                                data_dir=str(tmp_path / "data"), train=False)
        assert not val.synthetic and len(val) >= 1


class TestSequenceBuckets:
    """data/pack.py's ragged-sequence packers — the serving engine's shape
    contract (ISSUE 10 satellite): bucket choice, static packing, and the
    unpack round-trip that drops every pad position."""

    def test_bucket_for_picks_smallest_fitting_rung(self):
        from distributed_pytorch_training_tpu.data.pack import bucket_for

        assert bucket_for(1, (8, 16, 32)) == 8
        assert bucket_for(8, (8, 16, 32)) == 8
        assert bucket_for(9, (32, 8, 16)) == 16  # unsorted ladder is fine
        assert bucket_for(32, (8, 16, 32)) == 32

    def test_bucket_for_rejects_oversize_and_empty(self):
        from distributed_pytorch_training_tpu.data.pack import bucket_for

        with pytest.raises(ValueError, match="exceeds the largest bucket"):
            bucket_for(33, (8, 16, 32))
        with pytest.raises(ValueError, match=">= 1"):
            bucket_for(0, (8,))

    def test_pack_token_rows_shapes_and_filler(self):
        from distributed_pytorch_training_tpu.data.pack import (
            pack_token_rows,
        )

        seqs = [np.arange(3, dtype=np.int32), np.arange(7, dtype=np.int32)]
        ids, lengths, weight = pack_token_rows(seqs, bucket=8, rows=4,
                                               pad_id=0)
        assert ids.shape == (4, 8) and ids.dtype == np.int32
        np.testing.assert_array_equal(lengths, [3, 7, 0, 0])
        np.testing.assert_array_equal(weight, [1.0, 1.0, 0.0, 0.0])
        np.testing.assert_array_equal(ids[0, :3], seqs[0])
        assert (ids[0, 3:] == 0).all() and (ids[2:] == 0).all()

    def test_pack_token_rows_rejects_misfits(self):
        from distributed_pytorch_training_tpu.data.pack import (
            pack_token_rows,
        )

        with pytest.raises(ValueError, match="do not fit"):
            pack_token_rows([np.ones(2, np.int32)] * 3, bucket=8, rows=2)
        with pytest.raises(ValueError, match="exceeds bucket"):
            pack_token_rows([np.ones(9, np.int32)], bucket=8, rows=2)
        with pytest.raises(ValueError, match="not 1-D"):
            pack_token_rows([np.ones((2, 2), np.int32)], bucket=8, rows=2)

    def test_unpack_round_trips_per_request_outputs(self):
        """Pack -> per-position compute -> unpack recovers each request's
        own rows exactly, with every pad position (tail pad AND filler
        rows) dropped."""
        from distributed_pytorch_training_tpu.data.pack import (
            pack_token_rows, unpack_token_rows,
        )

        rng = np.random.RandomState(0)
        seqs = [rng.randint(0, 99, n).astype(np.int32) for n in (5, 8, 1)]
        ids, lengths, _ = pack_token_rows(seqs, bucket=8, rows=4)
        # a per-position "output": position value + 1000*row, so any
        # cross-row or cross-position mixup is visible
        outputs = (ids.astype(np.float64)
                   + 1000.0 * np.arange(4)[:, None])
        out = unpack_token_rows(outputs, lengths, n_real=len(seqs))
        assert len(out) == 3
        for i, s in enumerate(seqs):
            assert out[i].shape == (len(s),)
            np.testing.assert_array_equal(out[i], s + 1000.0 * i)
