"""Fused int8 codec kernels (ops/quantize.py, ISSUE 6 tentpole 2).

The binding contract (PARITY.md): the Pallas kernels are BIT-IDENTICAL to
the XLA-composed reference codecs in parallel/grad_sync.py — same absmax,
same ``max(amax, 1e-30) * (1/127)`` scale, same round/clip, same fp32
dequant-sum reduction order. On the CPU tier-1 backend they run in
interpreter mode (forced here via ``fused=True`` — the gate itself keeps
CPU on the XLA-composed reference by default), so what these tests pin is
the kernel's arithmetic, and the TPU run only changes the scheduling.

Three layers:
* kernel-level bit-identity on TPU-shaped and edge-case vectors (acceptance
  criterion: "bit-identical to `_quantize_int8_rows` on TPU-shaped test
  vectors, interpreter mode in tier-1");
* gate/selection semantics (`resolve_fused`: explicit config beats the
  DPT_FUSED_QUANTIZE env, which beats the TPU-only backend default);
* whole-step bitwise parity: an `int8_multihop` training run with the
  kernel path selected lands bit-for-bit where the XLA-composed run lands
  (the int8 parity suites "pass unchanged with the kernel path selected" —
  bit-identical codecs compose to a bit-identical trajectory).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_training_tpu.ops.quantize import (
    FUSED_QUANTIZE_ENV, dequant_sum_rows_fused, fused_quantize_default,
    quantize_backend_supported, quantize_int8_rows_fused, resolve_fused,
)
from distributed_pytorch_training_tpu.parallel.grad_sync import (
    _dequant_sum_rows, _quantize_int8_rows,
)

# TPU-shaped vectors (the codec's real shapes: n replicas x a bucket chunk,
# chunk a multiple of nothing in particular) plus the edge cases.
SHAPES = [(8, 16384),   # a real bucket: 8 replicas x 64KiB/4 chunk
          (4, 128),     # exactly one lane block
          (3, 200),     # ragged: padding in the last block
          (1, 5),       # single row, sub-lane chunk
          (2, 1),       # degenerate chunk
          (16, 1000)]   # many rows, ragged


def _rand_rows(shape, seed=0, scale=10.0):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randn(*shape).astype(np.float32) * scale)


class TestKernelBitIdentity:
    @pytest.mark.parametrize("shape", SHAPES)
    def test_quantize_bit_identical(self, shape):
        rows = _rand_rows(shape)
        q_ref, s_ref = _quantize_int8_rows(rows, fused=False)
        q_fused, s_fused = quantize_int8_rows_fused(rows)
        assert q_fused.dtype == jnp.int8 and s_fused.dtype == jnp.float32
        np.testing.assert_array_equal(np.asarray(q_ref), np.asarray(q_fused))
        np.testing.assert_array_equal(np.asarray(s_ref), np.asarray(s_fused))

    @pytest.mark.parametrize("shape", SHAPES)
    def test_dequant_sum_bit_identical(self, shape):
        q, s = _quantize_int8_rows(_rand_rows(shape, seed=1), fused=False)
        np.testing.assert_array_equal(
            np.asarray(_dequant_sum_rows(q, s, fused=False)),
            np.asarray(dequant_sum_rows_fused(q, s)))

    def test_zero_rows_hit_the_scale_floor(self):
        """All-zero rows exercise the 1e-30 floor: codes 0, scale
        1e-30/127 — identical on both paths (the floor is what keeps the
        divide finite)."""
        rows = jnp.zeros((3, 300), jnp.float32)
        q_ref, s_ref = _quantize_int8_rows(rows, fused=False)
        q_fused, s_fused = quantize_int8_rows_fused(rows)
        np.testing.assert_array_equal(np.asarray(q_ref), np.asarray(q_fused))
        np.testing.assert_array_equal(np.asarray(s_ref), np.asarray(s_fused))
        assert not np.any(np.isnan(np.asarray(s_fused)))

    def test_mixed_magnitude_rows(self):
        """Per-row scales are independent: a tiny row next to a huge row
        must not leak scale across rows on either path."""
        rows = jnp.stack([_rand_rows((400,), seed=2, scale=1e-6),
                          _rand_rows((400,), seed=3, scale=1e6),
                          jnp.zeros(400, jnp.float32)])
        q_ref, s_ref = _quantize_int8_rows(rows, fused=False)
        q_fused, s_fused = quantize_int8_rows_fused(rows)
        np.testing.assert_array_equal(np.asarray(q_ref), np.asarray(q_fused))
        np.testing.assert_array_equal(np.asarray(s_ref), np.asarray(s_fused))

    def test_grid_codes_roundtrip_exactly(self):
        """Values already ON the int8 grid quantize losslessly through the
        fused kernel, like the reference (TestMultihopCodec's grid case)."""
        scale = 0.125
        codes = np.arange(-127, 128, dtype=np.float32)
        rows = jnp.asarray((codes * scale)[None])
        q, s = quantize_int8_rows_fused(rows)
        np.testing.assert_array_equal(np.asarray(q)[0], codes.astype(np.int8))
        np.testing.assert_allclose(float(s[0]), scale, rtol=1e-7)

    def test_inside_jit(self):
        """The codecs run inside compiled steps — the kernels must lower
        (interpreter mode on CPU) under jit with identical results."""
        rows = _rand_rows((4, 300), seed=4)

        @jax.jit
        def f(r):
            q, s = quantize_int8_rows_fused(r)
            return q, s, dequant_sum_rows_fused(q, s)

        q, s, out = f(rows)
        q_ref, s_ref = _quantize_int8_rows(rows, fused=False)
        np.testing.assert_array_equal(np.asarray(q), np.asarray(q_ref))
        np.testing.assert_array_equal(
            np.asarray(out),
            np.asarray(_dequant_sum_rows(q_ref, s_ref, fused=False)))


class TestGate:
    def test_backend_gate_is_tpu_only(self):
        assert quantize_backend_supported("tpu")
        assert not quantize_backend_supported("cpu")
        assert not quantize_backend_supported("gpu")
        # tier-1 runs on CPU: the default must be the XLA-composed path
        assert jax.default_backend() == "cpu"
        assert not quantize_backend_supported()

    def test_env_override_beats_backend(self, monkeypatch):
        monkeypatch.setenv(FUSED_QUANTIZE_ENV, "1")
        assert fused_quantize_default() is True
        monkeypatch.setenv(FUSED_QUANTIZE_ENV, "0")
        assert fused_quantize_default() is False
        monkeypatch.setenv(FUSED_QUANTIZE_ENV, "bogus")  # ignored, not a crash
        assert fused_quantize_default() == quantize_backend_supported()

    def test_explicit_flag_beats_everything(self, monkeypatch):
        monkeypatch.setenv(FUSED_QUANTIZE_ENV, "0")
        assert resolve_fused(True) is True
        monkeypatch.setenv(FUSED_QUANTIZE_ENV, "1")
        assert resolve_fused(False) is False
        assert resolve_fused(None) is True  # None = auto: env decides

    def test_codecs_follow_the_resolved_gate(self, monkeypatch):
        """grad_sync's reference implementations must not silently call
        back into the kernels: fused=False IS the XLA-composed path even
        when the env forces the kernels on."""
        monkeypatch.setenv(FUSED_QUANTIZE_ENV, "1")
        rows = _rand_rows((2, 100), seed=5)
        # both paths still agree bit-for-bit, so equality can't distinguish
        # them — instead pin that fused=None routes through the kernel
        # wrapper (padding machinery accepts TPU-hostile widths) without
        # error, and fused=False never imports trouble
        q_auto, s_auto = _quantize_int8_rows(rows)          # kernel path
        q_ref, s_ref = _quantize_int8_rows(rows, fused=False)
        np.testing.assert_array_equal(np.asarray(q_auto), np.asarray(q_ref))
        np.testing.assert_array_equal(np.asarray(s_auto), np.asarray(s_ref))


class TestStepParity:
    """Whole-step bitwise parity on the CPU mesh (interpreter mode): the
    int8/int8_multihop trajectories are IDENTICAL with the kernel path
    selected — the acceptance criterion's 'parity tests pass unchanged'
    strengthened to bit-equality, which bit-identical codecs must give."""

    def _run(self, mesh8, steps=6, **cfg):
        from tests.test_grad_sync import _batch, _trainer

        t, s = _trainer(mesh8, **cfg)
        batch = _batch(mesh8)
        key = jax.random.PRNGKey(1)
        for _ in range(steps):
            s, _m = t._train_step(s, batch, key)
        return s

    def _assert_bitwise(self, a, b):
        for x, y in zip(jax.tree_util.tree_leaves(a.params),
                        jax.tree_util.tree_leaves(b.params)):
            np.testing.assert_array_equal(np.asarray(jax.device_get(x)),
                                          np.asarray(jax.device_get(y)))

    @pytest.mark.parametrize("wire", [
        # ~7 s; the gather-wire fused kernels stay pinned fast by the
        # paged-KV fused-scatter bitwise legs (same _quantize_int8_rows
        # kernels) and the gsync_int8_mh_fused matrix contract
        pytest.param("int8", marks=pytest.mark.slow),
        "int8_multihop",
    ])
    def test_fused_step_bitwise_equals_composed(self, mesh8, wire):
        base = dict(bucket_cap_mb=0.25, wire_dtype=wire)
        fused = self._run(mesh8, fused_quantize=True, **base)
        composed = self._run(mesh8, fused_quantize=False, **base)
        self._assert_bitwise(fused, composed)
        assert int(fused.step) == int(composed.step) == 6

    @pytest.mark.slow  # ~23 s; zero1 x multihop parity is pinned fast by test_grad_sync, fused-vs-composed by the fast [int8_multihop] leg
    def test_zero1_multihop_fused_bitwise(self, mesh8):
        """The zero1+multihop composition (compressed scatter + quantized
        delta gather) routes BOTH codec call sites through the kernels."""
        base = dict(zero1=True, wire_dtype="int8_multihop")
        fused = self._run(mesh8, fused_quantize=True, **base)
        composed = self._run(mesh8, fused_quantize=False, **base)
        self._assert_bitwise(fused, composed)
