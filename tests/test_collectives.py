"""Collectives tests on the 8-device CPU mesh.

Covers the parity surface for the reference's reduce_tensor/barrier usage
(/root/reference/train_ddp.py:159-167, :112) plus the ring/all-to-all
primitives the long-context path needs (SURVEY.md §5).
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from distributed_pytorch_training_tpu.parallel import (
    MeshSpec,
    build_mesh,
    collectives as cc,
)
from distributed_pytorch_training_tpu.parallel.collectives import shard_map
from distributed_pytorch_training_tpu.parallel.mesh import DATA, SEQ


def test_psum_matches_sum(mesh8):
    x = jnp.arange(8.0)

    def body(x):
        return cc.psum(jnp.sum(x), DATA, mesh=mesh8)

    out = shard_map(body, mesh=mesh8, in_specs=P(DATA), out_specs=P())(x)
    assert float(out) == float(x.sum())


def test_psum_passthrough_on_trivial_axis(mesh8):
    # On a mesh where the axis has size 1, psum must be the identity at trace
    # time (the reference's single-process passthrough, train_ddp.py:164-165).
    x = jnp.float32(3.5)
    out = cc.psum(x, "model", mesh=mesh8)  # model axis size 1
    assert out is x


def test_pmean(mesh8):
    x = jnp.arange(8.0)

    def body(x):
        return cc.pmean(jnp.sum(x), DATA, mesh=mesh8)

    out = shard_map(body, mesh=mesh8, in_specs=P(DATA), out_specs=P())(x)
    np.testing.assert_allclose(float(out), float(x.mean()), rtol=1e-6)


def test_ppermute_ring_rotates(devices):
    mesh = build_mesh(MeshSpec(data=1, seq=8), devices=devices)
    x = jnp.arange(8.0)

    def body(x):
        return cc.ppermute_ring(x, SEQ, shift=1)

    out = shard_map(body, mesh=mesh, in_specs=P(SEQ), out_specs=P(SEQ))(x)
    # shift=1 sends shard i to i+1, so position i holds the value from i-1.
    np.testing.assert_array_equal(np.asarray(out), np.roll(np.arange(8.0), 1))


def test_all_to_all_transposes_shards(devices):
    mesh = build_mesh(MeshSpec(data=1, seq=8), devices=devices)
    x = jnp.arange(64.0).reshape(8, 8)

    def body(x):  # x: (1, 8) per device
        return cc.all_to_all(x, SEQ, split_axis=1, concat_axis=0)

    out = shard_map(body, mesh=mesh, in_specs=P(SEQ, None), out_specs=P(None, SEQ))(x)
    # tiled all_to_all of row-shards into column-shards is a global identity:
    # the real check is that the per-device shard shape flipped (1,8)->(8,1)
    # and the values landed back in place.
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))
    assert out.addressable_shards[0].data.shape == (8, 1)


def test_psum_scatter_all_gather_compose_to_psum(mesh8):
    """reduce-scatter + all-gather IS an all-reduce: gathering the scattered
    chunks must reproduce psum exactly — the identity the ZeRO-1 update is
    built on (each replica updates its chunk between the two halves)."""
    x = jnp.arange(64.0).reshape(8, 8)

    def body(x):
        full = cc.psum(x, DATA, mesh=mesh8)          # (1, 8) rows summed
        chunk = cc.psum_scatter(x[0], DATA)           # this replica's 1/8
        regathered = cc.all_gather(chunk, DATA)       # back to the full sum
        return jnp.abs(regathered - full[0]).max()

    out = shard_map(body, mesh=mesh8, in_specs=P(DATA), out_specs=P())(x)
    assert float(out) == 0.0


def test_psum_scatter_chunk_ownership(mesh8):
    """Replica i's psum_scatter output is chunk i of the summed vector, in
    axis-index order — the ordering all_gather inverts (and the parameter-
    shard ownership rule of the zero1 update)."""
    x = jnp.ones((8, 8))

    def body(x):
        chunk = cc.psum_scatter(jnp.arange(8.0) * x[0], DATA)
        # every replica contributed [0..7], so chunk i = (8 * i,)
        idx = jax.lax.axis_index(DATA)
        return jnp.abs(chunk - 8.0 * idx).max()

    out = shard_map(body, mesh=mesh8, in_specs=P(DATA), out_specs=P())(x)
    assert float(out) == 0.0


def test_psum_scatter_and_all_gather_passthrough_on_trivial_axis(mesh8):
    # single-device convention: reduce over one replica keeping its one
    # chunk (and gathering one chunk) is the identity, at trace time
    x = jnp.arange(4.0)
    assert cc.psum_scatter(x, "model", mesh=mesh8) is x
    assert cc.all_gather(x, "model", mesh=mesh8) is x


def test_host_collectives_single_process():
    # Single-process passthroughs (jax.process_count()==1 in tests).
    cc.barrier()  # no-op, must not hang
    assert cc.broadcast_from_main({"a": 1})["a"] == 1
    assert cc.reduce_scalar(4.25) == 4.25
    assert cc.reduce_scalar(jnp.float32(2.0), op="max") == 2.0
    gathered = cc.host_all_gather(np.float32(7.0))
    assert np.asarray(gathered).shape[0] == 1


def test_gradient_sync_emerges_from_sharding(mesh8):
    """The DDP-reducer-equivalence test: a jitted loss over a data-sharded
    batch yields gradients identical to single-device full-batch gradients —
    gradient sync with no explicit collective (SURVEY.md §2b row 2)."""
    from distributed_pytorch_training_tpu.parallel import shard_batch

    w = jnp.ones((4,)) * 0.5
    x = np.random.RandomState(0).randn(16, 4).astype(np.float32)
    y = np.random.RandomState(1).randn(16).astype(np.float32)

    def loss(w, x, y):
        return jnp.mean((x @ w - y) ** 2)

    g_single = jax.grad(loss)(w, x, y)

    batch = shard_batch({"x": x, "y": y}, mesh8)
    g_mesh = jax.jit(jax.grad(loss))(w, batch["x"], batch["y"])
    np.testing.assert_allclose(np.asarray(g_mesh), np.asarray(g_single), rtol=1e-5)


def test_unknown_axis_raises(mesh8):
    import pytest

    with pytest.raises(KeyError, match="dtaa"):
        cc.psum(jnp.float32(1.0), "dtaa", mesh=mesh8)
