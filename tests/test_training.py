"""Training-layer tests: optimizer parity with torch, step semantics, padded
metrics, bf16 path, checkpoint roundtrip, DP-vs-single-device equivalence
(SURVEY.md §4 parity tests)."""

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from distributed_pytorch_training_tpu.parallel import shard_batch, shard_pytree
from distributed_pytorch_training_tpu.training import (
    TrainConfig, Trainer, TrainState, make_optimizer, make_schedule,
)
from distributed_pytorch_training_tpu.training.optim import adamw, sgd
from distributed_pytorch_training_tpu.training.tasks import (
    ImageClassificationTask, summarize, zero_metrics, add_metrics,
)


class TestOptimParityWithTorch:
    """The reference uses torch.optim.SGD(momentum, weight_decay) (ref
    :339-344). Verify our optax chain reproduces torch's parameter
    trajectory bit-for-bit-ish in fp32."""

    def test_sgd_momentum_wd_trajectory(self):
        import torch

        w0 = np.random.RandomState(0).randn(5).astype(np.float32)
        x = np.random.RandomState(1).randn(16, 5).astype(np.float32)
        y = np.random.RandomState(2).randn(16).astype(np.float32)

        # torch
        wt = torch.nn.Parameter(torch.tensor(w0.copy()))
        opt = torch.optim.SGD([wt], lr=0.1, momentum=0.9, weight_decay=5e-4)
        for _ in range(5):
            opt.zero_grad()
            loss = ((torch.tensor(x) @ wt - torch.tensor(y)) ** 2).mean()
            loss.backward()
            opt.step()

        # ours
        tx = sgd(0.1, momentum=0.9, weight_decay=5e-4)
        wj = jnp.asarray(w0)
        opt_state = tx.init(wj)
        loss_fn = lambda w: jnp.mean((x @ w - y) ** 2)
        for _ in range(5):
            g = jax.grad(loss_fn)(wj)
            updates, opt_state = tx.update(g, opt_state, wj)
            wj = optax.apply_updates(wj, updates)

        np.testing.assert_allclose(np.asarray(wj), wt.detach().numpy(),
                                   rtol=1e-5, atol=1e-6)

    def test_adamw_trajectory(self):
        import torch

        w0 = np.random.RandomState(0).randn(5).astype(np.float32)
        x = np.random.RandomState(1).randn(16, 5).astype(np.float32)
        y = np.random.RandomState(2).randn(16).astype(np.float32)

        wt = torch.nn.Parameter(torch.tensor(w0.copy()))
        opt = torch.optim.AdamW([wt], lr=1e-3, weight_decay=0.01)
        for _ in range(5):
            opt.zero_grad()
            ((torch.tensor(x) @ wt - torch.tensor(y)) ** 2).mean().backward()
            opt.step()

        tx = adamw(1e-3, weight_decay=0.01, grad_clip_norm=None)
        wj = jnp.asarray(w0)
        opt_state = tx.init(wj)
        loss_fn = lambda w: jnp.mean((x @ w - y) ** 2)
        for _ in range(5):
            g = jax.grad(loss_fn)(wj)
            updates, opt_state = tx.update(g, opt_state, wj)
            wj = optax.apply_updates(wj, updates)

        np.testing.assert_allclose(np.asarray(wj), wt.detach().numpy(),
                                   rtol=1e-4, atol=1e-6)

    def test_make_optimizer_unknown_raises(self):
        with pytest.raises(ValueError):
            make_optimizer("lion", 0.1)

    def test_schedules(self):
        s = make_schedule("constant", 0.1)
        assert float(s(0)) == pytest.approx(0.1) and float(s(1000)) == pytest.approx(0.1)
        c = make_schedule("cosine", 0.1, total_steps=100, warmup_steps=10)
        assert float(c(0)) == pytest.approx(0.0)
        assert float(c(10)) == pytest.approx(0.1, rel=1e-3)
        assert float(c(100)) < 0.01
        with pytest.raises(ValueError):
            make_schedule("cosine", 0.1)  # missing total_steps


def _tiny_setup(mesh, bf16=False, n=32, hw=8):
    """A small ResNet-ish setup usable on the CPU mesh."""
    from distributed_pytorch_training_tpu.models import get_model

    dtype = jnp.bfloat16 if bf16 else jnp.float32
    model = get_model("resnet18", num_classes=4, dtype=dtype, cifar_stem=True)
    task = ImageClassificationTask(mean=(0.5, 0.5, 0.5), std=(0.25, 0.25, 0.25),
                                   augment=False, compute_dtype=dtype)
    trainer = Trainer(task, mesh, TrainConfig(seed=0, print_freq=1000))
    tx = sgd(0.005, momentum=0.9, weight_decay=0.0)
    rng = np.random.RandomState(0)
    images = rng.randint(0, 256, (n, hw, hw, 3)).astype(np.uint8)
    labels = (images.astype(np.float32).mean(axis=(1, 2, 3)) > 127).astype(np.int32)
    state = trainer.init_state(model, np.zeros((1, hw, hw, 3), np.float32), tx,
                               jax.random.PRNGKey(0))
    return trainer, state, images, labels


class TestTrainStep:
    @pytest.mark.slow
    def test_loss_decreases(self, mesh8):
        trainer, state, images, labels = _tiny_setup(mesh8)
        batch = shard_batch({"image": images, "label": labels,
                             "weight": np.ones(len(images), np.float32)}, mesh8)
        key = jax.random.PRNGKey(0)
        losses = []
        for _ in range(15):
            state, metrics = trainer._train_step(state, batch, key)
            losses.append(float(metrics["loss_sum"]) / float(metrics["weight"]))
        assert np.mean(losses[-5:]) < np.mean(losses[:5])

    def test_padding_weights_excluded(self, mesh8):
        """A batch padded with weight-0 junk must produce identical loss and
        gradient direction to the unpadded batch (drop_last=False parity,
        SURVEY.md §7 hard part (a))."""
        trainer, state, images, labels = _tiny_setup(mesh8, n=24)
        w_real = np.ones(24, np.float32)
        # pad 24 -> 32 with garbage rows, weight 0
        pad_img = np.concatenate([images, 255 * np.ones((8, 8, 8, 3), np.uint8)])
        pad_lab = np.concatenate([labels, np.zeros(8, np.int32)])
        pad_w = np.concatenate([w_real, np.zeros(8, np.float32)])

        task = trainer.task
        # compare loss via the eval path (no augmentation randomness)
        b_pad = shard_batch({"image": pad_img, "label": pad_lab, "weight": pad_w}, mesh8)
        m_pad = trainer._eval_step(state, b_pad)
        # unpadded 24-sample batch: shard over 8 devices needs 24 % 8 == 0: ok
        b_raw = shard_batch({"image": images, "label": labels, "weight": w_real}, mesh8)
        m_raw = trainer._eval_step(state, b_raw)
        assert float(m_pad["weight"]) == float(m_raw["weight"]) == 24.0
        np.testing.assert_allclose(float(m_pad["loss_sum"]),
                                   float(m_raw["loss_sum"]), rtol=1e-5)

    @pytest.mark.slow
    def test_bf16_compute_fp32_params(self, mesh8):
        trainer, state, images, labels = _tiny_setup(mesh8, bf16=True)
        for leaf in jax.tree_util.tree_leaves(state.params):
            assert leaf.dtype == jnp.float32  # params stay fp32 (AMP parity)
        batch = shard_batch({"image": images, "label": labels,
                             "weight": np.ones(len(images), np.float32)}, mesh8)
        state2, metrics = trainer._train_step(state, batch, jax.random.PRNGKey(0))
        assert np.isfinite(float(metrics["loss_sum"]))
        for leaf in jax.tree_util.tree_leaves(state2.params):
            assert leaf.dtype == jnp.float32

    @pytest.mark.slow
    def test_step_counter_increments(self, mesh8):
        trainer, state, images, labels = _tiny_setup(mesh8)
        batch = shard_batch({"image": images, "label": labels,
                             "weight": np.ones(len(images), np.float32)}, mesh8)
        before = int(state.step)
        state2, _ = trainer._train_step(state, batch, jax.random.PRNGKey(0))
        assert int(state2.step) == before + 1


class TestMetricsHelpers:
    def test_summarize(self):
        m = {"loss_sum": jnp.asarray(10.0), "correct": jnp.asarray(3.0),
             "weight": jnp.asarray(4.0)}
        loss, acc = summarize(m)
        assert loss == pytest.approx(2.5) and acc == pytest.approx(75.0)

    def test_summarize_empty(self):
        loss, acc = summarize(zero_metrics())
        assert np.isnan(loss) and np.isnan(acc)

    def test_add(self):
        a = {"loss_sum": jnp.asarray(1.0), "correct": jnp.asarray(1.0),
             "weight": jnp.asarray(2.0)}
        out = add_metrics(a, a)
        assert float(out["weight"]) == 4.0


class TestCheckpoint:
    @pytest.mark.slow
    def test_roundtrip(self, mesh8, tmp_path):
        from distributed_pytorch_training_tpu.training.checkpoint import (
            CheckpointManager,
        )

        trainer, state, images, labels = _tiny_setup(mesh8)
        batch = shard_batch({"image": images, "label": labels,
                             "weight": np.ones(len(images), np.float32)}, mesh8)
        state, _ = trainer._train_step(state, batch, jax.random.PRNGKey(0))

        mgr = CheckpointManager(str(tmp_path / "ckpt"))
        mgr.save(1, state, wait=True)

        # fresh template with different params
        _, template, _, _ = _tiny_setup(mesh8)
        restored = mgr.restore_latest(template)
        assert restored is not None
        rstate, epoch, step_in_epoch = restored
        assert epoch == 1 and step_in_epoch == 0 and int(rstate.step) == 1
        for a, b in zip(jax.tree_util.tree_leaves(rstate.params),
                        jax.tree_util.tree_leaves(state.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        mgr.close()

    def test_restore_empty_returns_none(self, mesh8, tmp_path):
        from distributed_pytorch_training_tpu.training.checkpoint import (
            CheckpointManager,
        )

        _, state, _, _ = _tiny_setup(mesh8)
        mgr = CheckpointManager(str(tmp_path / "empty"))
        assert mgr.restore_latest(state) is None
        mgr.close()


class TestLMTasks:
    """LanguageModelingTask / MaskedLMTask semantics on a tiny GPT-2/BERT."""

    def _lm_setup(self, mesh, model_name="gpt2_124m", seq=16, task=None):
        from distributed_pytorch_training_tpu.models import get_model
        from distributed_pytorch_training_tpu.training.tasks import (
            LanguageModelingTask,
        )

        model = get_model(model_name, depth=2, hidden_dim=64, num_heads=2,
                          vocab_size=128, max_position=seq)
        task = task or LanguageModelingTask()
        trainer = Trainer(task, mesh, TrainConfig(seed=0, print_freq=1000))
        tx = adamw(1e-3, grad_clip_norm=1.0)
        state = trainer.init_state(model, np.zeros((1, seq), np.int32), tx,
                                   jax.random.PRNGKey(0))
        return trainer, state

    @pytest.mark.slow
    def test_lm_loss_decreases(self, mesh8):
        trainer, state = self._lm_setup(mesh8)
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 128, (16, 16)).astype(np.int32)
        batch = shard_batch({"input_ids": ids,
                             "weight": np.ones(16, np.float32)}, mesh8)
        losses = []
        for _ in range(10):
            state, m = trainer._train_step(state, batch, jax.random.PRNGKey(1))
            losses.append(float(m["loss_sum"]) / float(m["weight"]))
        assert losses[-1] < losses[0]

    def test_mlm_loss_only_on_masked(self, mesh8):
        from distributed_pytorch_training_tpu.models import get_model
        from distributed_pytorch_training_tpu.training.tasks import MaskedLMTask

        model = get_model("bert_base", depth=2, hidden_dim=64, num_heads=2,
                          vocab_size=128, max_position=16)
        task = MaskedLMTask(vocab_size=128, mask_token_id=3)
        trainer = Trainer(task, mesh8, TrainConfig(seed=0, print_freq=1000))
        tx = adamw(1e-3, grad_clip_norm=1.0)
        state = trainer.init_state(model, np.zeros((1, 16), np.int32), tx,
                                   jax.random.PRNGKey(0))
        ids = np.random.RandomState(0).randint(0, 128, (16, 16)).astype(np.int32)
        batch = shard_batch({"input_ids": ids,
                             "weight": np.ones(16, np.float32)}, mesh8)
        m = trainer._eval_step(state, batch)
        # ~15% of 256 positions selected; weight must be well below the
        # full-position count and above zero
        assert 0 < float(m["weight"]) < 100

    def test_lm_weight_mask_excludes_padded_rows(self, mesh8):
        from distributed_pytorch_training_tpu.training.tasks import (
            LanguageModelingTask,
        )

        trainer, state = self._lm_setup(mesh8)
        ids = np.random.RandomState(0).randint(0, 128, (16, 16)).astype(np.int32)
        w = np.ones(16, np.float32)
        w[8:] = 0.0  # half the rows are padding
        batch = shard_batch({"input_ids": ids, "weight": w}, mesh8)
        m = trainer._eval_step(state, batch)
        assert float(m["weight"]) == 8 * 15  # 8 real rows x (seq-1) targets


class TestGradAccumulation:
    """grad_accum=k must reproduce the unaccumulated step on the same global
    batch for DETERMINISTIC per-sample losses: the weighted-grad combination
    d(global mean) = sum_i (w_i/W) d(mean_i) is exact, not an approximation.
    (Stochastic tasks and batch-statistic aux losses are unbiased but not
    bit-equal — see the equivalence-scope note in loop.py.)"""

    def _setup(self, mesh, accum, lr=1e-2):
        from distributed_pytorch_training_tpu.models.gpt2 import GPT2LMHead
        from distributed_pytorch_training_tpu.training import (
            TrainConfig, Trainer,
        )
        from distributed_pytorch_training_tpu.training.optim import sgd
        from distributed_pytorch_training_tpu.training.tasks import (
            LanguageModelingTask,
        )

        model = GPT2LMHead(vocab_size=64, hidden_dim=32, depth=2, num_heads=2,
                           max_position=16)
        t = Trainer(LanguageModelingTask(), mesh,
                    TrainConfig(seed=0, grad_accum=accum))
        state = t.init_state(model, np.zeros((1, 16), np.int32), sgd(lr),
                             jax.random.PRNGKey(0))
        return t, state

    def _batch(self, mesh, n=16):
        from distributed_pytorch_training_tpu.parallel import shard_batch

        rng = np.random.RandomState(0)
        w = np.ones(n, np.float32)
        w[-3:] = 0.0  # padding rows: the weighted combination must be exact
        return shard_batch({
            "input_ids": rng.randint(0, 64, (n, 16)).astype(np.int32),
            "weight": w,
        }, mesh)

    def test_accum_matches_unaccumulated(self, mesh8):
        batch = self._batch(mesh8)
        key = jax.random.PRNGKey(1)
        t1, s1 = self._setup(mesh8, accum=1)
        t4, s4 = self._setup(mesh8, accum=4)
        s1n, m1 = t1._train_step(s1, batch, key)
        s4n, m4 = t4._train_step(s4, batch, key)
        np.testing.assert_allclose(float(m1["loss_sum"]),
                                   float(m4["loss_sum"]), rtol=1e-5)
        np.testing.assert_allclose(float(m1["weight"]), float(m4["weight"]))
        # updated params identical (same grads -> same SGD step)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-6),
            jax.device_get(s1n.params), jax.device_get(s4n.params))

    def _setup_bn(self, mesh, accum):
        from distributed_pytorch_training_tpu.data import (
            CIFAR10_MEAN, CIFAR10_STD,
        )
        from distributed_pytorch_training_tpu.models import get_model
        from distributed_pytorch_training_tpu.training import (
            TrainConfig, Trainer,
        )
        from distributed_pytorch_training_tpu.training.optim import sgd
        from distributed_pytorch_training_tpu.training.tasks import (
            ImageClassificationTask,
        )

        model = get_model("resnet18", num_classes=10, cifar_stem=True)
        t = Trainer(ImageClassificationTask(mean=CIFAR10_MEAN,
                                            std=CIFAR10_STD, augment=False),
                    mesh, TrainConfig(seed=0, grad_accum=accum))
        state = t.init_state(model, np.zeros((1, 32, 32, 3), np.float32),
                             sgd(0.1), jax.random.PRNGKey(0))
        return t, state

    @pytest.mark.slow
    def test_accum_batchnorm_parity(self, mesh8):
        """VERDICT r4 weak #5: grad_accum must serve the reference's own
        model family (ResNet/BatchNorm, train_ddp.py:154). Each microbatch
        normalizes by its own statistics (torch-equivalent under
        accumulation), so grads are close-not-exact; running stats get ONE
        EMA update from the weighted-mean microbatch statistics, so the
        batch-stats MEANS match the unaccumulated step exactly (up to fp
        reassociation) and the vars differ only by the within/between-
        microbatch variance decomposition."""
        from distributed_pytorch_training_tpu.parallel import shard_batch

        rng = np.random.RandomState(3)
        batch = shard_batch({
            "image": rng.randint(0, 255, (64, 32, 32, 3)).astype(np.uint8),
            "label": rng.randint(0, 10, 64).astype(np.int32),
            "weight": np.ones(64, np.float32),
        }, mesh8)
        key = jax.random.PRNGKey(1)
        t1, s1 = self._setup_bn(mesh8, accum=1)
        t2, s2 = self._setup_bn(mesh8, accum=2)
        s1n, m1 = t1._train_step(s1, batch, key)
        s2n, m2 = t2._train_step(s2, batch, key)
        # the loss itself shifts slightly: each microbatch normalizes by its
        # own BN statistics (observed ~0.15% on random data)
        np.testing.assert_allclose(float(m1["loss_sum"]),
                                   float(m2["loss_sum"]), rtol=5e-3)
        flat1 = jax.tree_util.tree_leaves_with_path(
            jax.device_get(s1n.batch_stats))
        flat2 = dict(jax.tree_util.tree_leaves_with_path(
            jax.device_get(s2n.batch_stats)))
        # Means would be exact at the FIRST BN layer (mean-of-microbatch-
        # means == full mean), but deeper layers see activations that were
        # normalized per-microbatch upstream, so everything drifts by
        # O(1/|mb|): observed max ~1e-4 abs on means, vars additionally
        # carry the within/between-microbatch decomposition gap.
        for path, leaf1 in flat1:
            leaf2 = flat2[path]
            name = jax.tree_util.keystr(path)
            tol = 1e-2 if "mean" in name else 0.15
            np.testing.assert_allclose(np.asarray(leaf2), np.asarray(leaf1),
                                       rtol=tol, atol=tol,
                                       err_msg=f"batch_stats diverged: {name}")
        # updated params close in absolute terms (BN couples samples within
        # a microbatch so grads are not bit-exact, and near-zero init makes
        # relative comparison meaningless; observed max |delta| ~0.015 at
        # lr=0.1 on random data)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=0, atol=0.05),
            jax.device_get(s1n.params), jax.device_get(s2n.params))

    def test_accum_rejects_indivisible_batch(self, mesh8):
        t, state = self._setup(mesh8, accum=3)
        batch = self._batch(mesh8, n=16)  # 16 % 3 != 0
        with pytest.raises(ValueError, match="not divisible"):
            t._train_step(state, batch, jax.random.PRNGKey(1))


class TestSeedDeterminism:
    """SURVEY §4: same seed -> identical training trajectory (the
    reproducibility contract behind ref set_seed, train_ddp.py:76-78/:319);
    different seed -> different trajectory (the seed actually reaches the
    stochastic parts: init, augmentation, shuffle)."""

    def _run(self, mesh, seed, steps=4):
        from distributed_pytorch_training_tpu.data import (
            CIFAR10_MEAN, CIFAR10_STD,
        )
        from distributed_pytorch_training_tpu.models import get_model
        from distributed_pytorch_training_tpu.parallel import shard_batch
        from distributed_pytorch_training_tpu.training import (
            TrainConfig, Trainer,
        )
        from distributed_pytorch_training_tpu.training.optim import sgd
        from distributed_pytorch_training_tpu.training.tasks import (
            ImageClassificationTask,
        )

        model = get_model("resnet18", num_classes=10)
        t = Trainer(ImageClassificationTask(mean=CIFAR10_MEAN,
                                            std=CIFAR10_STD, augment=True),
                    mesh, TrainConfig(seed=seed))
        state = t.init_state(model, np.zeros((1, 32, 32, 3), np.float32),
                             sgd(0.1, momentum=0.9),
                             jax.random.PRNGKey(seed))
        rng = np.random.RandomState(0)  # DATA fixed; only framework seed varies
        batch = shard_batch({
            "image": rng.randint(0, 256, (16, 32, 32, 3)).astype(np.uint8),
            "label": rng.randint(0, 10, 16).astype(np.int32),
            "weight": np.ones(16, np.float32),
        }, mesh)
        key = jax.random.fold_in(jax.random.PRNGKey(seed), 0)
        losses = []
        for _ in range(steps):
            state, m = t._train_step(state, batch, key)
            losses.append(float(m["loss_sum"]))
        return losses

    @pytest.mark.slow
    def test_same_seed_identical_trajectory(self, mesh8):
        a = self._run(mesh8, seed=42)
        b = self._run(mesh8, seed=42)
        np.testing.assert_array_equal(a, b)  # bit-identical, not just close

    @pytest.mark.slow
    def test_different_seed_different_trajectory(self, mesh8):
        a = self._run(mesh8, seed=42)
        c = self._run(mesh8, seed=43)
        assert a != c


class TestStepProfilerLifecycle:
    """StepProfiler must never leak an open jax.profiler session: a leaked
    session fails every later start_trace in the process and drops the
    partial trace (the train.py epoch loop context-manages it)."""

    def test_closes_on_exception(self, tmp_path):
        from distributed_pytorch_training_tpu.utils.profiling import (
            StepProfiler,
        )

        prof = StepProfiler(str(tmp_path / "t1"), 0, 5)
        with pytest.raises(RuntimeError, match="mid-epoch boom"):
            with prof:
                prof(0)  # enters the window -> start_trace fires
                assert prof._active
                raise RuntimeError("mid-epoch boom")
        assert not prof._active
        # the session really closed: a fresh trace can start (an open
        # session would raise here)
        jax.profiler.start_trace(str(tmp_path / "t2"))
        jax.profiler.stop_trace()

    def test_close_idempotent_and_noop_outside_window(self, tmp_path):
        from distributed_pytorch_training_tpu.utils.profiling import (
            StepProfiler,
        )

        with StepProfiler(str(tmp_path / "t3"), 5, 8) as prof:
            prof(0)  # before the window: no trace started
            assert not prof._active
        prof.close()  # double close is safe
        assert not prof._active

    @pytest.fixture
    def counted_profiler(self, monkeypatch):
        """jax.profiler start/stop replaced by counters: these edge-case
        tests assert session bookkeeping, not trace contents — and a
        start/stop imbalance must fail the test, not poison the process's
        real profiler for every later test."""
        calls = {"start": 0, "stop": 0}
        monkeypatch.setattr(
            jax.profiler, "start_trace",
            lambda log_dir, **kw: calls.__setitem__(
                "start", calls["start"] + 1))
        monkeypatch.setattr(
            jax.profiler, "stop_trace",
            lambda: calls.__setitem__("stop", calls["stop"] + 1))
        return calls

    def test_window_entirely_past_end_of_run(self, tmp_path,
                                             counted_profiler):
        """A --profile-steps window the run never reaches (short run, or a
        preemption before the window): the close() path must be a no-op —
        no session opened, none closed, no crash."""
        from distributed_pytorch_training_tpu.utils.profiling import (
            StepProfiler,
        )

        with StepProfiler(str(tmp_path / "never"), 100, 110) as prof:
            for step in range(5):  # run ends long before step 100
                prof(step)
        assert counted_profiler == {"start": 0, "stop": 0}
        assert not prof._active and not prof._done

    def test_run_ends_inside_window_closes_once(self, tmp_path,
                                                counted_profiler):
        """End-of-run INSIDE the window: __exit__ must stop the open
        session exactly once (close is the stop path, and a second close
        must not double-stop)."""
        from distributed_pytorch_training_tpu.utils.profiling import (
            StepProfiler,
        )

        with StepProfiler(str(tmp_path / "mid"), 2, 50) as prof:
            for step in range(5):  # enters the window, never reaches 50
                prof(step)
            assert prof._active
        assert counted_profiler == {"start": 1, "stop": 1}
        prof.close()
        assert counted_profiler == {"start": 1, "stop": 1}

    def test_restart_mid_window_no_double_start(self, tmp_path,
                                                counted_profiler):
        """The Supervisor-restart shape: a step failure fires mid-window,
        the step counter replays from the restore point, and the SAME
        profiler keeps being called (train.py ignores --profile-dir under
        --max-restarts precisely because a replayed window would lie — but
        the object must still never leak a session or start_trace twice).
        The restart replays steps whose _seen indices re-enter the window:
        _active guards the re-entry, _done guards re-arming after stop."""
        from distributed_pytorch_training_tpu.utils.profiling import (
            StepProfiler,
        )

        with StepProfiler(str(tmp_path / "restart"), 2, 6) as prof:
            with pytest.raises(RuntimeError, match="injected"):
                for step in range(8):
                    prof(step)  # enters the window at _seen == 2
                    if step == 3:
                        raise RuntimeError("injected step failure")
            assert counted_profiler == {"start": 1, "stop": 0}
            # the supervisor restores and the epoch replays: the hook keeps
            # firing; _seen advances through the stop boundary
            for step in range(8):
                prof(step)
        # ONE session start, ONE stop — the replay neither restarted the
        # trace nor left it open at exit
        assert counted_profiler == {"start": 1, "stop": 1}
        assert prof._done and not prof._active
