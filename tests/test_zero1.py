"""ZeRO-1 cross-replica weight-update sharding (training/loop.py `zero1`).

The contract (ISSUE 1 acceptance): on the same data-parallel mesh, the
sharded update must (a) train the SAME trajectory as the replicated
DDP-style update — layout is a performance fact, not a math fact — for both
SGD-momentum and AdamW, including the grad-accum and bf16 variants; (b)
actually replace the gradient all-reduces with reduce-scatter + all-gather
in the compiled HLO (the static census, experiments/trace_analysis.py); and
(c) round-trip its flat-sharded optimizer state through a checkpoint.

Tolerances: SGD parity is tight (the update is elementwise in the gradient,
so reduce-ordering differences stay proportional). AdamW's params get a
looser absolute tolerance: elements whose gradient is ~0 (qkv biases at
init) see Adam's normalization amplify fp reassociation noise into
O(lr * eps-ratio) update differences — inherent to ANY reduce-ordering
change, not a bug; the loss trajectory is the binding contract and stays
tight.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_training_tpu.models.gpt2 import GPT2LMHead
from distributed_pytorch_training_tpu.parallel import (
    MeshSpec, build_mesh, shard_batch,
)
from distributed_pytorch_training_tpu.training import TrainConfig, Trainer
from distributed_pytorch_training_tpu.training.optim import adamw, sgd
from distributed_pytorch_training_tpu.training.tasks import LanguageModelingTask

SEQ = 16
VOCAB = 64
DP_AXES = ("data", "fsdp")


def _tiny_gpt2():
    return GPT2LMHead(vocab_size=VOCAB, hidden_dim=32, depth=2, num_heads=2,
                      max_position=SEQ)


def _make_tx(name, shard_axes=None):
    if name == "sgd":
        # momentum + weight decay: the torch-parity chain (optim.sgd) —
        # fully elementwise, needs no shard awareness
        return sgd(0.1, momentum=0.9, weight_decay=5e-4)
    # clip active (1.0) so the psum'd global-norm path is exercised
    return adamw(1e-2, grad_clip_norm=1.0, shard_axes=shard_axes)


def _trainer(mesh, opt, zero1, grad_accum=1, bf16=False):
    t = Trainer(LanguageModelingTask(
                    compute_dtype=jnp.bfloat16 if bf16 else jnp.float32),
                mesh,
                TrainConfig(seed=0, zero1=zero1, grad_accum=grad_accum,
                            bf16=bf16))
    tx = _make_tx(opt, shard_axes=DP_AXES if zero1 else None)
    state = t.init_state(_tiny_gpt2(), np.zeros((1, SEQ), np.int32), tx,
                         jax.random.PRNGKey(0))
    return t, state


def _batch(mesh, n=16, pad_tail=0):
    rng = np.random.RandomState(0)
    w = np.ones(n, np.float32)
    if pad_tail:
        w[-pad_tail:] = 0.0  # loader-style padded rows
    return shard_batch({
        "input_ids": rng.randint(0, VOCAB, (n, SEQ)).astype(np.int32),
        "weight": w,
    }, mesh)


def _run_pair(mesh, opt, steps=6, grad_accum=1, bf16=False, pad_tail=0):
    """(replicated, zero1) trajectories: per-step losses + final states."""
    batch = _batch(mesh, pad_tail=pad_tail)
    key = jax.random.PRNGKey(1)
    out = []
    for zero1 in (False, True):
        t, s = _trainer(mesh, opt, zero1, grad_accum=grad_accum, bf16=bf16)
        losses = []
        for _ in range(steps):
            s, m = t._train_step(s, batch, key)
            losses.append(float(m["loss_sum"]) / max(float(m["weight"]), 1.0))
        out.append((losses, s))
    return out


def _assert_params_close(a, b, **tol):
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_allclose(
            np.asarray(jax.device_get(x)), np.asarray(jax.device_get(y)),
            **tol),
        a.params, b.params)


@pytest.mark.slow  # ~6 s; the adamw leg stays fast and is the stricter parity (two moments + bias correction through the sharded update)
def test_zero1_sgd_momentum_matches_replicated(mesh8):
    (l_rep, s_rep), (l_z1, s_z1) = _run_pair(mesh8, "sgd")
    np.testing.assert_allclose(l_rep, l_z1, rtol=2e-5)
    _assert_params_close(s_rep, s_z1, rtol=1e-4, atol=1e-6)
    assert l_rep[-1] < l_rep[0]


def test_zero1_adamw_matches_replicated(mesh8):
    (l_rep, s_rep), (l_z1, s_z1) = _run_pair(mesh8, "adamw")
    np.testing.assert_allclose(l_rep, l_z1, rtol=2e-5)
    # see module docstring for why AdamW params get an absolute tolerance
    _assert_params_close(s_rep, s_z1, rtol=2e-2, atol=2e-3)
    assert l_rep[-1] < l_rep[0]


def test_zero1_moments_actually_sharded(mesh8):
    """The memory win must be real: every AdamW moment lives as a 1-D
    flat-padded chunk of 1/8 the parameter's padded size per device —
    not a replicated copy with a sharded-looking spec."""
    _, state = _trainer(mesh8, "adamw", zero1=True)
    mu = state.opt_state[1].mu
    n_checked = 0
    for path, leaf in jax.tree_util.tree_leaves_with_path(mu):
        param = state.params
        for k in path:
            param = param[k.key]
        padded = param.size + (-param.size % 8)
        assert leaf.ndim == 1 and leaf.shape == (padded,), (path, leaf.shape)
        shard = leaf.addressable_shards[0].data
        assert shard.shape == (padded // 8,), (path, shard.shape)
        n_checked += 1
    assert n_checked >= 10
    # params themselves stay replicated (zero1 shards the UPDATE, not the
    # model — the DDP layout)
    wte = state.params["wte"]["embedding"]
    assert wte.sharding.is_fully_replicated


@pytest.mark.slow
def test_zero1_grad_accum_matches_replicated_grad_accum(mesh8):
    """grad_accum=2 inside the sharded step: the scan carry holds gradient
    SHARDS; the trajectory must still match the replicated accum path."""
    (l_rep, s_rep), (l_z1, s_z1) = _run_pair(mesh8, "sgd", steps=4,
                                             grad_accum=2)
    np.testing.assert_allclose(l_rep, l_z1, rtol=2e-5)
    _assert_params_close(s_rep, s_z1, rtol=1e-4, atol=1e-6)


@pytest.mark.slow
def test_zero1_bf16_matches_replicated_bf16(mesh8):
    """bf16 compute: forward math is per-sample identical in both layouts
    (params and the gradient sync stay fp32), so parity holds at bf16-noise
    tolerance."""
    (l_rep, s_rep), (l_z1, s_z1) = _run_pair(mesh8, "sgd", steps=4,
                                             bf16=True)
    np.testing.assert_allclose(l_rep, l_z1, rtol=1e-3)
    _assert_params_close(s_rep, s_z1, rtol=1e-3, atol=1e-4)


def test_zero1_padded_batch_rows(mesh8):
    """Weight-0 rows (the loader's padded last batch) must not skew the
    sharded update: shard-local weighted means recombine by weight."""
    (l_rep, _), (l_z1, _) = _run_pair(mesh8, "sgd", steps=3, pad_tail=4)
    np.testing.assert_allclose(l_rep, l_z1, rtol=2e-5)


@pytest.mark.slow  # ~8 s; strictly redundant with the zero1 contract in the matrix gate (same census, same rules)
def test_zero1_hlo_census_reduce_scatter_replaces_all_reduce(mesh8):
    """The acceptance check: the compiled zero1 step carries NO gradient-
    sized all-reduce; reduce-scatter + all-gather appear instead. Scalar
    psums (metrics, clip norm) are allowed — the census floor excludes
    them."""
    from distributed_pytorch_training_tpu.experiments.trace_analysis import (
        verify_zero1_collectives, weight_update_census,
    )

    batch = _batch(mesh8)
    key = jax.random.PRNGKey(1)
    texts = {}
    for zero1 in (False, True):
        t, s = _trainer(mesh8, "adamw", zero1)
        texts[zero1] = t._train_step.lower(s, batch, key).compile().as_text()

    # min_elements=128: the per-device HLO shards the 2048-element wte
    # gradient to 256 elements; every remaining zero1 all-reduce is a scalar
    verdict = verify_zero1_collectives(texts[False], texts[True],
                                       min_elements=128)
    assert verdict["replicated"]["all-reduce"] > 0
    assert verdict["zero1"]["all-reduce"] == 0
    assert verdict["zero1"]["reduce-scatter"] > 0
    assert verdict["zero1"]["all-gather"] > 0
    # and the replicated step has no reason to reduce-scatter
    rep = weight_update_census(texts[False], min_elements=128)
    assert rep["reduce-scatter"] == 0


@pytest.mark.slow
def test_zero1_checkpoint_roundtrip(mesh8, tmp_path):
    """Orbax save/restore of the flat-sharded optimizer state: restored
    leaves keep the template's dp sharding and exact values, and the
    restored run continues the trajectory bit-for-bit."""
    from distributed_pytorch_training_tpu.training.checkpoint import (
        CheckpointManager,
    )

    batch = _batch(mesh8)
    key = jax.random.PRNGKey(1)
    t, state = _trainer(mesh8, "adamw", zero1=True)
    state, _ = t._train_step(state, batch, key)

    ckpt = CheckpointManager(str(tmp_path / "ckpt"))
    ckpt.save(1, state, wait=True)

    t2, template = _trainer(mesh8, "adamw", zero1=True)
    restored, epoch, step_in_epoch = ckpt.restore_latest(template)
    ckpt.close()
    assert epoch == 1 and step_in_epoch == 0
    assert int(restored.step) == 1

    mu = restored.opt_state[1].mu["wte"]["embedding"]
    flat = [a for e in mu.sharding.spec if e is not None
            for a in ((e,) if isinstance(e, str) else e)]
    assert "data" in flat, mu.sharding  # dp sharding survived the roundtrip
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b))),
        state.opt_state, restored.opt_state)

    # the restored trajectory continues identically
    s_a, m_a = t._train_step(state, batch, key)
    s_b, m_b = t2._train_step(restored, batch, key)
    np.testing.assert_array_equal(np.asarray(m_a["loss_sum"]),
                                  np.asarray(m_b["loss_sum"]))


def test_zero1_single_shard_is_replicated_passthrough(devices):
    """zero1 on one batch shard = the replicated path (the single-device
    passthrough convention): same compiled step, no collectives."""
    mesh1 = build_mesh(MeshSpec(data=1), devices=devices[:1])
    t, s = _trainer(mesh1, "sgd", zero1=True)
    assert not t._zero1  # identity passthrough engaged
    batch = _batch(mesh1, n=4)
    s, m = t._train_step(s, batch, jax.random.PRNGKey(1))
    assert np.isfinite(float(m["loss_sum"]))


def test_zero1_single_shard_passthrough_via_harness_adamw(devices):
    """The bench canary path (EXTRA_CONFIGS *_zero1 on one chip): AdamW's
    clip must NOT carry shard axes when the Trainer runs the replicated
    fallback — a psum over unbound axis names is a trace-time crash, not a
    passthrough."""
    from distributed_pytorch_training_tpu.experiments.harness import (
        build_trainer, make_synth_batch,
    )

    trainer, state, mesh = build_trainer(
        devices[:1], False, "gpt2_124m", 32,
        lm_overrides=dict(hidden_dim=32, depth=1, num_heads=2),
        zero1=True)
    assert not trainer._zero1
    batch, _ = make_synth_batch(mesh, "gpt2_124m", 2, 32)
    state, m = trainer._train_step(state, batch, jax.random.PRNGKey(0))
    assert np.isfinite(float(m["loss_sum"]))


def test_zero1_rejects_non_dp_non_model_meshes(devices):
    """SP/PP/EP axes need the replicated update; a zero1 request there must
    fail loudly at construction, not silently mis-shard. (A `model` axis is
    the exception since ISSUE 7: zero1 composes with TP via the per-leaf
    GSPMD update — test_zero1_tp_* below.)"""
    mesh = build_mesh(MeshSpec(data=2, seq=2, model=2), devices=devices)
    with pytest.raises(ValueError, match="zero1"):
        Trainer(LanguageModelingTask(), mesh, TrainConfig(zero1=True))


def test_zero1_tp_gspmd_matches_replicated(devices):
    """zero1 x TP (the ISSUE 7 satellite): on a mesh with a model axis the
    update shards per-leaf via GSPMD flat-padded sharding constraints
    (training/loop.py _zero1_gspmd_apply) instead of the manual shard_map —
    and the trajectory must match the replicated update exactly (same
    gradients, same optimizer math, different layout)."""
    mesh_tp = build_mesh(MeshSpec(data=4, model=2), devices=devices)
    batch = _batch(mesh_tp)
    key = jax.random.PRNGKey(1)
    out = {}
    for zero1 in (False, True):
        t = Trainer(LanguageModelingTask(compute_dtype=jnp.float32),
                    mesh_tp, TrainConfig(seed=0, zero1=zero1),
                    rules=GPT2LMHead.partition_rules())
        assert t._zero1_gspmd == zero1  # per-leaf path, not the manual one
        assert not t._zero1
        # stock clip: the GSPMD update runs on GLOBAL flat arrays
        s = t.init_state(_tiny_gpt2(), np.zeros((1, SEQ), np.int32),
                         _make_tx("sgd"), jax.random.PRNGKey(0))
        losses = []
        for _ in range(4):
            s, m = t._train_step(s, batch, key)
            losses.append(float(m["loss_sum"]) / float(m["weight"]))
        out[zero1] = (losses, s)
    np.testing.assert_allclose(out[False][0], out[True][0], rtol=2e-5)
    _assert_params_close(out[False][1], out[True][1], rtol=1e-4, atol=1e-6)
    # moments born flat-sharded over the batch axes (1/4 per replica here):
    # every non-scalar optimizer leaf is 1-D flat-padded and NOT replicated
    n_checked = 0
    for path, leaf in jax.tree_util.tree_leaves_with_path(
            out[True][1].opt_state):
        if getattr(leaf, "ndim", 0) >= 1 and leaf.size >= 8:
            assert leaf.ndim == 1, (path, leaf.shape)
            assert not leaf.sharding.is_fully_replicated, path
            n_checked += 1
    assert n_checked >= 10


def test_zero1_tp_rejects_compressed_wire(devices):
    """The GSPMD path's scatter/gather are layout constraints, not
    explicit collectives — the wire codecs cannot wrap them; a compressed
    wire request there must fail loudly with the reason."""
    mesh_tp = build_mesh(MeshSpec(data=4, model=2), devices=devices)
    with pytest.raises(ValueError, match="GSPMD"):
        Trainer(LanguageModelingTask(), mesh_tp,
                TrainConfig(zero1=True, wire_dtype="int8"),
                rules=GPT2LMHead.partition_rules())


def test_zero1_rejects_fsdp_rule_conflict(devices):
    """fsdp-sharded params + zero1 is a layout contradiction (zero1 assumes
    replicated params); the error must name the choice."""
    mesh = build_mesh(MeshSpec(data=2, fsdp=4), devices=devices)
    with pytest.raises(ValueError, match="fsdp"):
        Trainer(LanguageModelingTask(), mesh, TrainConfig(zero1=True),
                rules=GPT2LMHead.partition_rules())


@pytest.mark.slow
def test_zero1_resnet_batchnorm_trains(mesh8):
    """BatchNorm models under zero1: per-shard statistics (torch DDP's
    per-GPU BN semantics) — the loss must still go down and the EMAs move."""
    from distributed_pytorch_training_tpu.data import CIFAR10_MEAN, CIFAR10_STD
    from distributed_pytorch_training_tpu.models import get_model
    from distributed_pytorch_training_tpu.training.tasks import (
        ImageClassificationTask,
    )

    t = Trainer(ImageClassificationTask(mean=CIFAR10_MEAN, std=CIFAR10_STD,
                                        augment=False),
                mesh8, TrainConfig(seed=0, zero1=True))
    model = get_model("resnet18", num_classes=10, cifar_stem=True)
    state = t.init_state(model, np.zeros((1, 32, 32, 3), np.float32),
                         sgd(0.05, momentum=0.9, weight_decay=5e-4),
                         jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    batch = shard_batch({
        "image": rng.randint(0, 256, (16, 32, 32, 3)).astype(np.uint8),
        "label": rng.randint(0, 10, 16).astype(np.int32),
        "weight": np.ones(16, np.float32),
    }, mesh8)
    stats0 = jax.device_get(state.batch_stats)
    losses = []
    key = jax.random.PRNGKey(1)
    for _ in range(8):
        state, m = t._train_step(state, batch, key)
        losses.append(float(m["loss_sum"]) / float(m["weight"]))
    assert losses[-1] < losses[0], losses
    moved = jax.tree_util.tree_map(
        lambda a, b: float(np.abs(np.asarray(jax.device_get(a))
                                  - np.asarray(b)).max()),
        state.batch_stats, stats0)
    assert max(jax.tree_util.tree_leaves(moved)) > 0.0
