"""Concurrency discipline (ISSUE 18): the four static rules
(analysis/concurrency_rules.py) each get a mutation test (synthetic
violation flagged) and a false-positive test (idiomatic code stays
clean); the runtime tracer (utils/locktrace.py) gets zero-cost-when-off
pins and an on-mode recording suite; and the three PR-17 race fixes get
deterministic regression tests that a revert trips — through a rule, the
tracer cross-check, or the interleaving itself.
"""

import socket
import textwrap
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from distributed_pytorch_training_tpu.analysis.ast_rules import run_ast_rules
from distributed_pytorch_training_tpu.analysis.concurrency_rules import (
    check_runtime_consistency, lock_order_graph,
)
from distributed_pytorch_training_tpu.utils import locktrace


def _lint(tmp_path, source, rules, name="mod.py"):
    path = tmp_path / name
    path.write_text(textwrap.dedent(source))
    return run_ast_rules(files=[path], rules=rules)


def _rules_of(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# guarded-by
# ---------------------------------------------------------------------------


class TestGuardedBy:
    GUARDED = """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = []   # guarded-by: _lock
    """

    def test_mutation_unlocked_write_flags(self, tmp_path):
        src = self.GUARDED + """
            def bad(self):
                self.items.append(1)
        """
        findings = _lint(tmp_path, src, ["guarded-by"])
        assert _rules_of(findings) == {"guarded-by"}
        assert "items" in findings[0].message

    def test_mutation_unlocked_read_flags(self, tmp_path):
        src = self.GUARDED + """
            def bad(self):
                return len(self.items)
        """
        assert _lint(tmp_path, src, ["guarded-by"])

    def test_locked_access_is_clean(self, tmp_path):
        src = self.GUARDED + """
            def ok(self):
                with self._lock:
                    self.items.append(1)
                    return list(self.items)
        """
        assert _lint(tmp_path, src, ["guarded-by"]) == []

    def test_lock_held_contract_covers_helpers(self, tmp_path):
        """A helper documented `# lock-held: _lock` accesses guarded
        state freely — the caller's `with` is the acquisition site."""
        src = self.GUARDED + """
            def _helper(self):   # lock-held: _lock
                return self.items.pop()

            def ok(self):
                with self._lock:
                    return self._helper()
        """
        assert _lint(tmp_path, src, ["guarded-by"]) == []

    def test_init_is_exempt(self, tmp_path):
        """Construction precedes sharing: the __init__ that declares the
        guard writes the attribute lock-free by definition."""
        src = """
            import threading

            class C:
                def __init__(self, seed):
                    self._lock = threading.Lock()
                    self.items = [seed]   # guarded-by: _lock
                    self.items.append(seed + 1)
        """
        assert _lint(tmp_path, src, ["guarded-by"]) == []

    def test_nested_function_resets_held_set(self, tmp_path):
        """A closure defined under `with self._lock` runs LATER, on an
        arbitrary thread — lexical position is not lock coverage."""
        src = self.GUARDED + """
            def bad(self):
                with self._lock:
                    def cb():
                        return self.items.pop()
                return cb
        """
        assert _lint(tmp_path, src, ["guarded-by"])

    def test_suppression_on_the_line(self, tmp_path):
        src = self.GUARDED + """
            def snapshot(self):
                return len(self.items)  # analysis: disable=guarded-by
        """
        assert _lint(tmp_path, src, ["guarded-by"]) == []

    def test_unannotated_attrs_are_ignored(self, tmp_path):
        src = """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.free = 0

                def f(self):
                    self.free += 1
        """
        assert _lint(tmp_path, src, ["guarded-by"]) == []

    def test_class_attr_guard(self, tmp_path):
        """Class-level shared state (the Request._ids idiom) is matched
        through ClassName.attr too."""
        src = """
            import threading

            class C:
                _ids = iter(range(9))   # guarded-by: _ids_lock
                _ids_lock = threading.Lock()

                def ok(self):
                    with C._ids_lock:
                        return next(C._ids)

                def bad(self):
                    return next(C._ids)
        """
        findings = _lint(tmp_path, src, ["guarded-by"])
        assert len(findings) == 1 and findings[0].location.endswith(":13")


# ---------------------------------------------------------------------------
# lock-order-acyclic
# ---------------------------------------------------------------------------


class TestLockOrderAcyclic:
    def test_mutation_two_file_cycle_flags(self, tmp_path):
        """The graph is global: each file's nesting is locally consistent,
        the cycle only exists over the union."""
        a = tmp_path / "a.py"
        b = tmp_path / "b.py"
        a.write_text(textwrap.dedent("""
            import threading

            class A:
                _lock = threading.Lock()

                def f(self):
                    with A._lock:
                        with B._lock:
                            pass
        """))
        b.write_text(textwrap.dedent("""
            import threading

            class B:
                _lock = threading.Lock()

                def g(self):
                    with B._lock:
                        with A._lock:
                            pass
        """))
        findings = run_ast_rules(files=[a, b], rules=["lock-order-acyclic"])
        assert _rules_of(findings) == {"lock-order-acyclic"}
        assert "A._lock" in findings[0].message
        assert "B._lock" in findings[0].message

    def test_consistent_order_is_clean(self, tmp_path):
        src = """
            import threading

            class A:
                _lock = threading.Lock()

                def f(self):
                    with A._lock:
                        with B._lock:
                            pass

                def g(self):
                    with A._lock:
                        with B._lock:
                            pass

            class B:
                _lock = threading.Lock()
        """
        assert _lint(tmp_path, src, ["lock-order-acyclic"]) == []

    def test_module_level_lock_identity(self, tmp_path):
        src = """
            import threading

            _REGISTRY_LOCK = threading.Lock()

            class A:
                _lock = threading.Lock()

                def f(self):
                    with _REGISTRY_LOCK:
                        with A._lock:
                            pass

                def g(self):
                    with A._lock:
                        with _REGISTRY_LOCK:
                            pass
        """
        findings = _lint(tmp_path, src, ["lock-order-acyclic"],
                         name="locks.py")
        assert len(findings) == 1
        assert "locks._REGISTRY_LOCK" in findings[0].message

    def test_suppression_on_the_reported_site(self, tmp_path):
        src = """
            import threading

            class A:
                _lock = threading.Lock()

                def f(self):
                    with A._lock:
                        with B._lock:  # analysis: disable=lock-order-acyclic
                            pass

            class B:
                _lock = threading.Lock()

                def g(self):
                    with B._lock:
                        with A._lock:
                            pass
        """
        # the finding anchors at the first (sorted) cycle site — the
        # line carrying the disable — so nothing survives
        assert _lint(tmp_path, src, ["lock-order-acyclic"]) == []

    def test_repo_graph_is_acyclic(self):
        """The real tree's lexical acquisition graph must stay a DAG —
        this is the whole-repo half of the tier-1 gate."""
        edges = lock_order_graph()
        assert check_runtime_consistency(set(), edges) == []


# ---------------------------------------------------------------------------
# no-blocking-under-lock
# ---------------------------------------------------------------------------


class TestNoBlockingUnderLock:
    def test_mutation_each_blocking_call_flags(self, tmp_path):
        for call in ("time.sleep(1)",
                     "urllib.request.urlopen('http://x')",
                     "socket.create_connection(('h', 1))",
                     "subprocess.run(['true'])",
                     "t.join()",
                     "fut.result(5.0)",
                     "self._q.get(timeout=1.0)"):
            src = f"""
                import socket
                import subprocess
                import threading
                import time
                import urllib.request

                LOCK = threading.Lock()

                def f(t, fut, self=None):
                    with LOCK:
                        {call}
            """
            findings = _lint(tmp_path, src, ["no-blocking-under-lock"])
            assert findings, f"did not flag under lock: {call}"

    def test_outside_the_with_is_clean(self, tmp_path):
        src = """
            import threading
            import time

            LOCK = threading.Lock()

            def f():
                with LOCK:
                    n = 1
                time.sleep(n)
        """
        assert _lint(tmp_path, src, ["no-blocking-under-lock"]) == []

    def test_str_join_is_not_thread_join(self, tmp_path):
        src = """
            import threading

            LOCK = threading.Lock()

            def f(parts):
                with LOCK:
                    return ", ".join(parts)
        """
        assert _lint(tmp_path, src, ["no-blocking-under-lock"]) == []

    def test_condition_wait_on_held_lock_is_exempt(self, tmp_path):
        """cv.wait RELEASES cv while blocked — the canonical pattern,
        not a hold-while-blocking bug."""
        src = """
            import threading

            class Q:
                def __init__(self):
                    self._cv = threading.Condition()

                def take(self, timeout):
                    with self._cv:
                        self._cv.wait(timeout)
        """
        assert _lint(tmp_path, src, ["no-blocking-under-lock"]) == []

    def test_suppression(self, tmp_path):
        src = """
            import threading
            import time

            LOCK = threading.Lock()

            def f():
                with LOCK:
                    time.sleep(0.1)  # analysis: disable=no-blocking-under-lock
        """
        assert _lint(tmp_path, src, ["no-blocking-under-lock"]) == []


# ---------------------------------------------------------------------------
# thread-lifecycle
# ---------------------------------------------------------------------------


class TestThreadLifecycle:
    def test_mutation_undaemonized_unjoined_flags(self, tmp_path):
        src = """
            import threading

            def f(fn):
                t = threading.Thread(target=fn)
                t.start()
        """
        findings = _lint(tmp_path, src, ["thread-lifecycle"])
        assert _rules_of(findings) == {"thread-lifecycle"}

    def test_daemon_kwarg_is_clean(self, tmp_path):
        src = """
            import threading

            def f(fn):
                t = threading.Thread(target=fn, daemon=True)
                t.start()
        """
        assert _lint(tmp_path, src, ["thread-lifecycle"]) == []

    def test_joined_elsewhere_in_file_is_clean(self, tmp_path):
        """The start/join pair commonly spans methods (start in run(),
        join in stop()) — the rule matches join sites file-wide."""
        src = """
            import threading

            class Server:
                def start(self, fn):
                    self._t = threading.Thread(target=fn)
                    self._t.start()

                def stop(self):
                    self._t.join(5.0)
        """
        assert _lint(tmp_path, src, ["thread-lifecycle"]) == []

    def test_daemon_attr_assignment_is_clean(self, tmp_path):
        src = """
            import threading

            def f(fn):
                t = threading.Thread(target=fn)
                t.daemon = True
                t.start()
        """
        assert _lint(tmp_path, src, ["thread-lifecycle"]) == []

    def test_suppression(self, tmp_path):
        src = """
            import threading

            def f(fn):
                t = threading.Thread(target=fn)  # analysis: disable=thread-lifecycle
                t.start()
        """
        assert _lint(tmp_path, src, ["thread-lifecycle"]) == []


def test_repo_is_clean_under_the_concurrency_rules():
    """The annotated tree carries zero findings from the four rules —
    the `analysis check` exit-0 half of the ISSUE 18 acceptance."""
    findings = run_ast_rules(rules=["guarded-by", "lock-order-acyclic",
                                    "no-blocking-under-lock",
                                    "thread-lifecycle"])
    assert findings == [], "\n".join(str(f) for f in findings)


# ---------------------------------------------------------------------------
# static <-> runtime consistency (the cross-check contract)
# ---------------------------------------------------------------------------


class TestRuntimeConsistency:
    STATIC = {("A.x", "B.y"): "mod.py:10"}

    def test_matching_order_is_consistent(self):
        assert check_runtime_consistency({("A.x", "B.y")},
                                         self.STATIC) == []

    def test_new_acyclic_edge_is_consistent(self):
        assert check_runtime_consistency({("B.y", "C.z")},
                                         self.STATIC) == []

    def test_reversed_edge_is_reported_with_the_static_site(self):
        msgs = check_runtime_consistency({("B.y", "A.x")}, self.STATIC)
        assert msgs and any("mod.py:10" in m for m in msgs)

    def test_runtime_edge_closing_a_cycle_is_reported(self):
        msgs = check_runtime_consistency({("B.y", "C.z"), ("C.z", "A.x")},
                                         self.STATIC)
        assert any("cycle" in m for m in msgs)


# ---------------------------------------------------------------------------
# locktrace: zero cost when off
# ---------------------------------------------------------------------------


@pytest.fixture
def lockcheck_off(monkeypatch):
    monkeypatch.delenv("DPT_LOCKCHECK", raising=False)


@pytest.fixture
def lockcheck_on(monkeypatch):
    monkeypatch.setenv("DPT_LOCKCHECK", "1")
    locktrace.trace().reset()
    yield
    locktrace.uninstall_probes()
    locktrace.trace().reset()


class TestLocktraceOff:
    def test_named_lock_is_a_plain_lock(self, lockcheck_off):
        lk = locktrace.named_lock("X._lock")
        assert type(lk) is type(threading.Lock())
        cv = locktrace.named_condition("X._cv")
        assert type(cv) is threading.Condition

    def test_no_recording(self, lockcheck_off):
        locktrace.trace().reset()
        with locktrace.named_lock("X._lock"):
            pass
        assert locktrace.trace().acquisitions == []

    def test_probes_are_a_no_op(self, lockcheck_off):
        orig = time.sleep
        locktrace.install_probes()
        try:
            assert time.sleep is orig
        finally:
            locktrace.uninstall_probes()

    def test_no_extra_threads(self, lockcheck_off):
        before = threading.active_count()
        locktrace.named_lock("X._lock")
        locktrace.named_condition("X._cv")
        assert threading.active_count() == before


class TestLocktraceOn:
    def test_nested_acquire_records_the_edge(self, lockcheck_on):
        a = locktrace.named_lock("A._lock")
        b = locktrace.named_lock("B._lock")
        assert isinstance(a, locktrace.TracedLock)
        with a:
            with b:
                pass
        tr = locktrace.trace()
        assert ("A._lock", "B._lock") in tr.order_edges()
        assert tr.acquisitions == [("A._lock",), ("A._lock", "B._lock")]
        assert tr.held_by_current_thread() == ()

    def test_condition_over_traced_lock_round_trips(self, lockcheck_on):
        cv = locktrace.named_condition("Q._cv")
        box = []

        def consumer():
            with cv:
                while not box:
                    cv.wait(5.0)
                box.append("seen")

        t = threading.Thread(target=consumer, daemon=True)
        t.start()
        time.sleep(0.05)
        with cv:
            box.append("item")
            cv.notify()
        t.join(timeout=5.0)
        assert not t.is_alive() and box == ["item", "seen"]
        assert any(name == "Q._cv" for acq in
                   locktrace.trace().acquisitions for name in acq)

    def test_probe_records_hold_while_blocking(self, lockcheck_on):
        locktrace.install_probes()
        try:
            with locktrace.named_lock("A._lock"):
                time.sleep(0.001)
            time.sleep(0.001)   # no lock held: uninteresting, not recorded
        finally:
            locktrace.uninstall_probes()
        events = locktrace.trace().blocking_events
        assert events == [("time.sleep", ("A._lock",))]

    def test_uninstall_restores_the_originals(self, lockcheck_on):
        orig_sleep, orig_conn = time.sleep, socket.create_connection
        locktrace.install_probes()
        assert time.sleep is not orig_sleep
        locktrace.uninstall_probes()
        assert time.sleep is orig_sleep
        assert socket.create_connection is orig_conn

    def test_cross_check_flags_a_reversal(self, lockcheck_on):
        assert locktrace.cross_check({("A.x", "B.y"), ("B.y", "A.x")})
        assert locktrace.cross_check({("A.x", "B.y")}) == []


# ---------------------------------------------------------------------------
# PR-17 regression: PagePool match-time claim (paged.py)
# ---------------------------------------------------------------------------


class TestPagePoolMatchTimeClaim:
    def test_matched_prefix_page_cannot_be_evicted_into_the_same_lease(
            self):
        """The race fix, replayed deterministically: a dry free list must
        evict some OTHER retained page for the fresh tail — never the
        prefix page this same alloc just matched. Reverting the
        match-time refcount bump re-leases one physical page at two
        logical offsets and the prefill scatter corrupts the shared
        prefix."""
        from distributed_pytorch_training_tpu.serving.paged import PagePool

        pool = PagePool(n_pages=3, page_size=1, pages_per_slot=2)
        first = pool.alloc([5, 6], 2)       # drains the free list
        assert first is not None and pool.free_pages() == 0
        pool.release(first)                 # both pages parked, retained
        lease = pool.alloc([5, 9], 2)       # prefix [5] matches; tail fresh
        assert lease is not None
        pages = [int(p) for p in lease.pages[:lease.n_pages]]
        assert len(set(pages)) == lease.n_pages, (
            f"one physical page leased at two offsets: {pages}")
        assert len(lease.shared) == 1
        assert lease.shared[0] not in pages[1:], (
            "the matched prefix page was evicted and re-leased as fresh")
        assert pool._ref[pages[0]] == 1 and pool._ref[pages[1]] == 1

    def test_failed_alloc_rolls_back_the_match_time_claims(self):
        """The claim-at-match-time bump must be undone when the tail
        cannot be covered — otherwise admission-control refusals leak
        refcounts and the prefix page never parks again."""
        from distributed_pytorch_training_tpu.serving.paged import PagePool

        pool = PagePool(n_pages=4, page_size=1, pages_per_slot=3)
        a = pool.alloc([5, 6, 7], 3)        # all three pages leased
        assert a is not None
        b = pool.alloc([5, 8, 9], 3)        # matches [5], tail uncoverable
        assert b is None
        assert pool._ref[int(a.pages[0])] == 1, (
            "rolled-back match left a refcount behind")


# ---------------------------------------------------------------------------
# PR-17 regression: router deadline + dead-vs-slow (router.py)
# ---------------------------------------------------------------------------


class _DyingReplica:
    """A replica whose every pending dies instantly — the resubmit loop's
    worst case."""

    def __init__(self, name):
        self.name = name

    def submit(self, tokens, **kw):
        return self

    def result(self, timeout=None):
        from distributed_pytorch_training_tpu.serving.router import (
            ReplicaDead)
        raise ReplicaDead(f"{self.name} died")

    def healthy(self):
        return True

    def queue_depth(self):
        return 0


class TestRouterDeadline:
    def test_spent_deadline_raises_instead_of_resubmitting_forever(self):
        """The race fix: with every replica dying instantly, result(T)
        must raise TimeoutError once T is spent — reverting the deadline
        check spins the resubmit loop unboundedly (this test would hang
        without the worker-thread guard)."""
        from distributed_pytorch_training_tpu.serving.router import Router

        router = Router([_DyingReplica("r0"), _DyingReplica("r1")])
        req = router.submit(np.ones(3, np.int32))
        outcome = []

        def wait():
            try:
                req.result(timeout=0.3)
                outcome.append("returned")
            except TimeoutError:
                outcome.append("timeout")
            except Exception as e:  # noqa: BLE001 - the regression signal
                outcome.append(repr(e))

        t = threading.Thread(target=wait, daemon=True)
        t.start()
        t.join(timeout=10.0)
        assert not t.is_alive(), "resubmit loop spun past the deadline"
        assert outcome == ["timeout"]
        assert req.replica_deaths >= 1

    def test_http_socket_timeout_is_slow_not_dead(self, monkeypatch):
        """A slow read surfaces as TimeoutError and leaves the health
        hint intact — resubmitting would stack a duplicate in-flight
        copy on a healthy-but-busy replica."""
        from distributed_pytorch_training_tpu.serving.router import (
            HttpReplica)

        replica = HttpReplica("r0", port=1)

        def _slow(req, timeout=None):
            raise socket.timeout("read timed out")

        monkeypatch.setattr(urllib.request, "urlopen", _slow)
        with pytest.raises(TimeoutError):
            replica.submit(np.ones(3, np.int32)).result(timeout=0.1)
        assert replica._last_ok is True and replica.healthy()

    def test_http_refused_connection_is_dead(self, monkeypatch):
        from distributed_pytorch_training_tpu.serving.router import (
            HttpReplica, ReplicaDead)

        replica = HttpReplica("r0", port=1)

        def _refuse(req, timeout=None):
            raise urllib.error.URLError(ConnectionRefusedError("refused"))

        monkeypatch.setattr(urllib.request, "urlopen", _refuse)
        with pytest.raises(ReplicaDead):
            replica.submit(np.ones(3, np.int32)).result(timeout=0.1)
        assert replica._last_ok is False and not replica.healthy()


# ---------------------------------------------------------------------------
# PR-17 regression: kill waits for the step boundary (continuous.py)
# ---------------------------------------------------------------------------


class TestKillStepInterleaving:
    def test_kill_blocks_until_step_releases_the_lock(self, monkeypatch):
        """Deterministic two-thread interleaving of the kill/step race:
        T1 parks inside step() (a gated decode_step) holding the
        scheduler lock; T2's kill() must BLOCK until the step boundary,
        and the request that step completes resolves as a RESULT, never
        double-resolved by the kill. Under DPT_LOCKCHECK=1 the traced
        acquisition order must agree with the static lock graph — the
        cross-method nesting (scheduler lock -> queue condition) only the
        tracer can see."""
        monkeypatch.setenv("DPT_LOCKCHECK", "1")
        locktrace.trace().reset()

        from distributed_pytorch_training_tpu.serving.batching import (
            RequestQueue)
        from distributed_pytorch_training_tpu.serving.continuous import (
            ContinuousScheduler)
        from distributed_pytorch_training_tpu.serving.paged import (
            PagedServeConfig)

        cfg = PagedServeConfig(buckets=(8,), rows=2, max_new_tokens=3,
                               page_size=4)
        in_decode = threading.Event()
        gate = threading.Event()

        class _GatedEngine:
            config = cfg
            _control = {"tok": np.zeros(cfg.rows, np.int32)}
            decodes = 0

            def set_page_row(self, slot, row):
                pass

            def admit(self, slot, tokens, want, temperature, top_p, seed):
                return cfg.buckets[-1]

            def decode_step(self):
                if self.decodes == 0:
                    in_decode.set()
                    assert gate.wait(10.0), "test gate never released"
                self.decodes += 1

            def fetch_slot(self, slot):
                return (np.zeros(cfg.max_new_tokens, np.int32),
                        np.zeros(7, np.float32))

        q = RequestQueue(cfg.buckets)
        sched = ContinuousScheduler(_GatedEngine(), q)
        req = q.submit(np.arange(4, dtype=np.int32), temperature=0.0)

        stepper = threading.Thread(target=sched.step, daemon=True)
        stepper.start()
        assert in_decode.wait(10.0)         # T1 holds _lock, mid-decode

        killer = threading.Thread(target=sched.kill, daemon=True)
        killer.start()
        killer.join(timeout=0.3)
        assert killer.is_alive(), (
            "kill() mutated scheduler state MID-STEP — the lock is gone")

        gate.set()                          # step boundary: both finish
        stepper.join(timeout=10.0)
        killer.join(timeout=10.0)
        assert not stepper.is_alive() and not killer.is_alive()

        # the step that was in flight completed its request as a result
        res = req.result(timeout=5.0)
        assert res.tokens.shape == (3,)
        assert sched.served == 1 and sched.killed

        # runtime orders agree with the static graph: the scheduler lock
        # nests OVER the queue condition (step -> _pull -> take), never
        # the reverse
        edges = locktrace.trace().order_edges()
        assert ("ContinuousScheduler._lock", "RequestQueue._cv") in edges
        assert ("RequestQueue._cv", "ContinuousScheduler._lock") \
            not in edges
        assert locktrace.cross_check() == []


# ---------------------------------------------------------------------------
# triage-fix regressions (the findings the rules surfaced on the tree)
# ---------------------------------------------------------------------------


class TestCapacityProbeOutsideLock:
    def test_reentrant_probe_does_not_deadlock(self):
        """The guarded-by/no-blocking triage fix: available() used to
        call the external probe while holding the watch lock — a probe
        that re-enters the registry (a cluster feed calling sync) then
        self-deadlocks on the non-reentrant lock. Run in a worker so a
        revert fails the assert instead of hanging the suite."""
        from distributed_pytorch_training_tpu.resilience.capacity import (
            CapacityWatch)

        watch = CapacityWatch(total=8, available=5)

        def probe():
            watch.sync(3)       # re-enters the watch's lock
            return 2

        watch._probe = probe
        out = []
        t = threading.Thread(target=lambda: out.append(watch.available()),
                             daemon=True)
        t.start()
        t.join(timeout=5.0)
        assert not t.is_alive(), "available() deadlocked on its own probe"
        assert out == [2]

    def test_probe_growth_sets_returned(self):
        from distributed_pytorch_training_tpu.resilience.capacity import (
            CapacityWatch)

        watch = CapacityWatch(total=8, available=2, probe=lambda: 6)
        watch.returned.clear()
        assert watch.available() == 6
        assert watch.returned.is_set()


class TestProfilerCaptureDirUnderLock:
    def test_armed_open_path_holds_the_lock_for_capture_dir(
            self, tmp_path, monkeypatch):
        """The triage fix: __call__'s armed-open path minted the capture
        directory WITHOUT the lock while capture() mints it under the
        lock — two concurrent draws could return the same name and mix
        sessions. Pin the invariant: _capture_dir always runs with the
        profiler lock held."""
        from distributed_pytorch_training_tpu.utils import profiling
        from distributed_pytorch_training_tpu.utils.profiling import (
            StepProfiler)

        prof = StepProfiler(str(tmp_path))
        held_at_call = []
        orig = StepProfiler._capture_dir

        def recording(self):
            held_at_call.append(self._lock.locked())
            return orig(self)

        monkeypatch.setattr(StepProfiler, "_capture_dir", recording)
        monkeypatch.setattr(profiling.jax.profiler, "start_trace",
                            lambda d: None)
        monkeypatch.setattr(profiling.jax.profiler, "stop_trace",
                            lambda: None)
        assert prof.request_capture(steps=1, reason="test")
        prof(0)     # opens the armed window: the fixed path
        prof(1)     # closes it
        with prof.capture(reason="test2") as d:   # the immediate path
            assert d is not None
        assert len(held_at_call) == 2
        assert all(held_at_call), (
            f"_capture_dir ran without the lock: {held_at_call}")
        dirs = {p.name for p in tmp_path.iterdir()}
        assert len(dirs) == 0 or len(dirs) == len(set(dirs))


# ---------------------------------------------------------------------------
# Recorder observer contract (ISSUE 18 satellite)
# ---------------------------------------------------------------------------


class TestRecorderObserverContract:
    def test_blocking_observer_does_not_hold_the_stream_lock(self):
        """Observers run OUTSIDE the recorder lock: an observer stuck in
        its callback must not block concurrent emit() or
        remove_observer() — reverting the snapshot-then-call structure
        deadlocks this test's second emit."""
        from distributed_pytorch_training_tpu import telemetry

        rec = telemetry.Recorder(None, ring_size=8)
        entered = threading.Event()
        release = threading.Event()

        def blocker(ev):
            if ev.get("name") == "blocker":
                entered.set()
                assert release.wait(10.0)

        rec.add_observer(blocker)
        t = threading.Thread(
            target=lambda: rec.emit("span", "blocker", dur_s=0.0),
            daemon=True)
        t.start()
        assert entered.wait(5.0)

        done = []

        def concurrent():
            rec.emit("span", "other", dur_s=0.0)   # must not wait on t
            rec.remove_observer(blocker)
            done.append(True)

        t2 = threading.Thread(target=concurrent, daemon=True)
        t2.start()
        t2.join(timeout=5.0)
        alive = t2.is_alive()
        release.set()
        t.join(timeout=5.0)
        assert not alive, (
            "emit/remove_observer blocked behind a stuck observer")
        # 3 = the init-time `meta` stream header + the two span events
        assert done == [True] and rec.n_events == 3

    def test_observer_exception_is_contained(self):
        from distributed_pytorch_training_tpu import telemetry

        rec = telemetry.Recorder(None, ring_size=8)
        rec.add_observer(lambda ev: (_ for _ in ()).throw(RuntimeError()))
        ev = rec.emit("span", "x", dur_s=0.0)
        assert ev["name"] == "x" and rec.n_events == 2


# ---------------------------------------------------------------------------
# PARITY: DPT_LOCKCHECK must not move a single device byte
# ---------------------------------------------------------------------------


class TestLockcheckParity:
    def test_hlo_is_bit_identical_on_and_off(self, monkeypatch):
        """The PARITY.md clause: locktrace is host-side only. The lowered
        HLO of a jitted computation must not depend on DPT_LOCKCHECK in
        any way."""
        import jax
        import jax.numpy as jnp

        def f(x):
            return jnp.tanh(x) @ x.T

        x = np.arange(12, dtype=np.float32).reshape(3, 4)
        monkeypatch.delenv("DPT_LOCKCHECK", raising=False)
        off = jax.jit(f).lower(x).as_text()
        monkeypatch.setenv("DPT_LOCKCHECK", "1")
        on = jax.jit(f).lower(x).as_text()
        assert on == off

    def test_recorder_stream_is_identical_modulo_timestamps(
            self, monkeypatch):
        from distributed_pytorch_training_tpu import telemetry

        def stream(env):
            if env:
                monkeypatch.setenv("DPT_LOCKCHECK", "1")
            else:
                monkeypatch.delenv("DPT_LOCKCHECK", raising=False)
            rec = telemetry.Recorder(None, ring_size=8, run_id="pin",
                                     gen=0, rank=0)
            rec.emit("span", "step", dur_s=0.5)
            rec.emit("gauge", "depth", value=3)
            return [{k: v for k, v in ev.items() if k != "ts"}
                    for ev in rec.ring]

        assert stream(False) == stream(True)
