"""MoE layer + expert parallelism (models/moe.py).

The dense-einsum top-k routing must (a) reduce to a plain MLP in the
single-expert no-drop limit, (b) respect capacity, (c) train end-to-end with
expert weights sharded over the ``expert`` mesh axis.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_training_tpu.models import get_model
from distributed_pytorch_training_tpu.models.moe import (
    GPT2MoELMHead,
    MoeMlp,
)
from distributed_pytorch_training_tpu.parallel import MeshSpec, build_mesh


def test_single_expert_no_drop_equals_dense_mlp():
    """E=1, top_k=1, ample capacity: routing is the identity (gate=1), so the
    MoE layer must equal gelu(x@wi)@wo exactly."""
    x = jnp.asarray(np.random.RandomState(0).randn(2, 8, 16), jnp.float32)
    layer = MoeMlp(num_experts=1, hidden_dim=32, top_k=1, capacity_factor=2.0)
    params = layer.init(jax.random.PRNGKey(0), x)["params"]
    y = layer.apply({"params": params}, x)
    wi, wo = params["wi"][0], params["wo"][0]
    want = jax.nn.gelu(x.reshape(-1, 16) @ wi) @ wo
    np.testing.assert_allclose(np.asarray(y).reshape(-1, 16),
                               np.asarray(want), rtol=1e-5, atol=1e-5)


def test_capacity_drops_overflow_tokens():
    """With capacity 1 slot/expert, at most E tokens can be processed; the
    rest must contribute exactly zero (residual carries them)."""
    n, e = 16, 2
    x = jnp.asarray(np.random.RandomState(1).randn(1, n, 8), jnp.float32)
    layer = MoeMlp(num_experts=e, hidden_dim=16, top_k=1,
                   capacity_factor=e / n)  # cap = 1
    params = layer.init(jax.random.PRNGKey(0), x)["params"]
    y = np.asarray(layer.apply({"params": params}, x))[0]
    nonzero_rows = (np.abs(y) > 1e-9).any(axis=-1).sum()
    assert nonzero_rows <= e


def test_aux_loss_sown_and_finite():
    x = jnp.asarray(np.random.RandomState(2).randn(2, 8, 16), jnp.float32)
    layer = MoeMlp(num_experts=4, hidden_dim=32)
    variables = layer.init(jax.random.PRNGKey(0), x)
    _, mut = layer.apply({"params": variables["params"]}, x,
                         mutable=["losses"])
    (aux,) = jax.tree_util.tree_leaves(mut["losses"])
    # Switch aux loss is >= 1 (perfect balance) and small at init
    assert np.isfinite(float(aux)) and 0.5 < float(aux) < 4.0


def test_capacity_scales_with_top_k():
    """top_k=2 doubles the routing assignments, so capacity must scale by k
    (ADVICE r1: the old ceil(S/E*cf) covered only ~62% of 2S assignments).

    With E=2 and top_k=2 every token routes to BOTH experts — each expert
    gets exactly S assignments. Correct capacity at cf=1.0 is S (no drops),
    so the output must equal the ample-capacity (cf=4.0) reference; the old
    S/E formula gave cap=S/2 and dropped half the assignments."""
    s, e = 16, 2
    x = jnp.asarray(np.random.RandomState(3).randn(1, s, 8), jnp.float32)
    tight = MoeMlp(num_experts=e, hidden_dim=16, top_k=2, capacity_factor=1.0)
    ample = MoeMlp(num_experts=e, hidden_dim=16, top_k=2, capacity_factor=4.0)
    params = tight.init(jax.random.PRNGKey(0), x)["params"]
    y_tight = np.asarray(tight.apply({"params": params}, x))
    y_ample = np.asarray(ample.apply({"params": params}, x))
    np.testing.assert_allclose(y_tight, y_ample, rtol=1e-5, atol=1e-6)


@pytest.mark.slow  # ~5 s convergence smoke; routing/dispatch exactness stays fast via the sorted-dispatch parity legs
def test_router_noise_trains_through_lm_task():
    """router_noise > 0 at train time must not raise (ADVICE r1: the task
    previously omitted the rngs dict, so make_rng('dropout') failed) and must
    actually jitter routing across rng keys."""
    from distributed_pytorch_training_tpu.training.tasks import (
        MoeLanguageModelingTask,
    )
    from distributed_pytorch_training_tpu.training.train_state import TrainState
    from distributed_pytorch_training_tpu.training.optim import sgd

    model = get_model("gpt2_moe", vocab_size=64, hidden_dim=16, depth=2,
                      num_heads=2, num_experts=4, max_position=16,
                      router_noise=0.5)
    ids = np.random.RandomState(0).randint(0, 64, (2, 16)).astype(np.int32)
    variables = model.init(jax.random.PRNGKey(0), jnp.asarray(ids),
                           train=False)
    state = TrainState.create(apply_fn=model.apply,
                              params=variables["params"], tx=sgd(0.1))
    task = MoeLanguageModelingTask()
    batch = {"input_ids": jnp.asarray(ids),
             "weight": jnp.ones(2, jnp.float32)}
    loss1, _ = task.loss_and_metrics(state, state.params, batch,
                                     jax.random.PRNGKey(1), train=True)
    loss2, _ = task.loss_and_metrics(state, state.params, batch,
                                     jax.random.PRNGKey(2), train=True)
    assert np.isfinite(float(loss1)) and np.isfinite(float(loss2))
    # different rng -> different router jitter -> (generically) different loss
    assert float(loss1) != float(loss2)


def test_gpt2_moe_forward_and_registry():
    model = get_model("gpt2_moe", vocab_size=128, hidden_dim=32, depth=2,
                      num_heads=2, num_experts=4, max_position=32)
    assert isinstance(model, GPT2MoELMHead)
    ids = jnp.zeros((2, 16), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), ids, train=False)
    logits = model.apply(variables, ids, train=False)
    assert logits.shape == (2, 16, 128)
    assert np.isfinite(np.asarray(logits)).all()


@pytest.mark.slow
def test_moe_trains_expert_parallel(devices):
    """Full jitted train step with experts sharded over a real expert axis
    (expert=4 x data=2 mesh on 8 virtual devices): the EP all-to-alls XLA
    inserts must compile and produce finite loss + nonzero expert grads."""
    from distributed_pytorch_training_tpu.parallel import shard_batch
    from distributed_pytorch_training_tpu.training import TrainConfig, Trainer
    from distributed_pytorch_training_tpu.training.optim import adamw
    from distributed_pytorch_training_tpu.training.tasks import (
        MoeLanguageModelingTask,
    )

    mesh = build_mesh(MeshSpec(expert=4, data=2), devices=devices)
    model = get_model("gpt2_moe", vocab_size=64, hidden_dim=16, depth=2,
                      num_heads=2, num_experts=4, max_position=16)
    task = MoeLanguageModelingTask()
    trainer = Trainer(task, mesh, TrainConfig(seed=0),
                      rules=GPT2MoELMHead.partition_rules())
    state = trainer.init_state(model, np.zeros((1, 16), np.int32),
                               adamw(1e-3), jax.random.PRNGKey(0))
    # expert weights really are sharded over the expert axis
    wi_shard = state.params["block1"]["moe"]["wi"].sharding.spec
    assert wi_shard[0] == "expert"
    wi_before = np.asarray(jax.device_get(state.params["block1"]["moe"]["wi"]))

    batch = shard_batch({
        "input_ids": np.random.RandomState(0).randint(0, 64, (8, 16)).astype(np.int32),
        "weight": np.ones(8, np.float32),
    }, mesh)
    # state is donated by the compiled step; snapshot taken above
    state2, metrics = trainer._train_step(state, batch, jax.random.PRNGKey(1))
    assert np.isfinite(float(metrics["loss_sum"]))
    wi_after = np.asarray(jax.device_get(state2.params["block1"]["moe"]["wi"]))
    assert np.abs(wi_after - wi_before).sum() > 0  # experts actually updated


@pytest.mark.slow
def test_moe_remat_trains(devices):
    """gpt2_moe with --remat: dense blocks checkpointed, MoE blocks (which
    sow the router aux loss) left plain — the step must still run and sow."""
    import numpy as np

    from distributed_pytorch_training_tpu.models import get_model
    from distributed_pytorch_training_tpu.parallel import (
        MeshSpec, build_mesh, shard_batch,
    )
    from distributed_pytorch_training_tpu.training import TrainConfig, Trainer
    from distributed_pytorch_training_tpu.training.optim import adamw
    from distributed_pytorch_training_tpu.training.tasks import (
        MoeLanguageModelingTask,
    )

    mesh = build_mesh(MeshSpec(data=4, expert=2), devices=devices)
    model = get_model("gpt2_moe", vocab_size=64, hidden_dim=16, depth=2,
                      num_heads=2, num_experts=2, max_position=16, remat=True)
    tr = Trainer(MoeLanguageModelingTask(), mesh, TrainConfig(seed=0),
                 rules=type(model).partition_rules())
    st = tr.init_state(model, np.zeros((1, 16), np.int32), adamw(1e-3),
                       jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    batch = shard_batch({
        "input_ids": rng.randint(0, 64, (8, 16)).astype(np.int32),
        "weight": np.ones(8, np.float32),
    }, mesh)
    st, m = tr._train_step(st, batch, jax.random.PRNGKey(1))
    assert np.isfinite(float(m["loss_sum"]))


class TestSortedDispatchParity:
    """The sort-based dispatch (VERDICT r3 #8) must be numerically
    interchangeable with the dense-einsum oracle — outputs, gradients, and
    the aux loss — while never materializing a (B, S, E, C) tensor."""

    def _pair(self, b=2, s=64, d=16, e=4, top_k=2, cf=1.25, seed=0):
        x = jnp.asarray(np.random.RandomState(seed).randn(b, s, d),
                        jnp.float32)
        kw = dict(num_experts=e, hidden_dim=32, top_k=top_k,
                  capacity_factor=cf)
        sort = MoeMlp(dispatch_mode="sorted", **kw)
        dense = MoeMlp(dispatch_mode="einsum", **kw)
        params = sort.init(jax.random.PRNGKey(0), x)  # same param tree
        return x, sort, dense, params

    @pytest.mark.parametrize("top_k", [1, 2])
    def test_outputs_match(self, top_k):
        x, sort, dense, params = self._pair(top_k=top_k)
        y_s = sort.apply(params, x)
        y_d = dense.apply(params, x)
        np.testing.assert_allclose(np.asarray(y_s), np.asarray(y_d),
                                   rtol=1e-5, atol=1e-5)

    def test_outputs_match_under_capacity_pressure(self):
        # cf low enough that experts overflow: the drop set (and hence the
        # output) must be identical, which pins the priority order too
        x, sort, dense, params = self._pair(e=2, top_k=2, cf=0.4, seed=3)
        y_s = sort.apply(params, x)
        y_d = dense.apply(params, x)
        np.testing.assert_allclose(np.asarray(y_s), np.asarray(y_d),
                                   rtol=1e-5, atol=1e-5)

    def test_gradients_and_aux_match(self):
        x, sort, dense, params = self._pair(seed=5)

        def loss(mod):
            def f(p, x):
                y, aux = mod.apply(p, x, mutable=["losses"])
                return (y ** 2).sum() + aux["losses"]["moe_aux"][0]
            return f

        l_s, g_s = jax.value_and_grad(loss(sort))(params, x)
        l_d, g_d = jax.value_and_grad(loss(dense))(params, x)
        np.testing.assert_allclose(float(l_s), float(l_d), rtol=1e-5)
        flat_s = jax.tree_util.tree_leaves(g_s)
        flat_d = jax.tree_util.tree_leaves(g_d)
        for a, b in zip(flat_s, flat_d):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    def test_no_dense_dispatch_tensor_in_jaxpr(self):
        """The whole point: no intermediate carries the S x E x C blowup.
        At E=32, S=256, cap=20 the dense path would build (1,256,32,20)
        f32 tensors; assert nothing that big (or E*C-shaped vs S) exists."""
        b, s, d, e = 1, 256, 16, 32
        x = jnp.zeros((b, s, d), jnp.float32)
        layer = MoeMlp(num_experts=e, hidden_dim=32, top_k=2,
                       dispatch_mode="sorted")
        params = layer.init(jax.random.PRNGKey(0), x)
        jaxpr = jax.make_jaxpr(lambda p, x: layer.apply(p, x))(params, x)
        cap = int(np.ceil(s * 2 / e * 1.25))
        forbidden = b * s * e * cap  # the dense dispatch tensor's size
        for eqn in jaxpr.jaxpr.eqns:
            for v in eqn.outvars:
                sz = int(np.prod(v.aval.shape)) if v.aval.shape else 1
                assert sz < forbidden, (
                    f"{eqn.primitive.name} materializes {v.aval.shape} — "
                    "the S*E*C dispatch blowup the sorted path must avoid")

    def test_32_experts_single_chip_shapes(self):
        """A 32-expert MoE block runs (the r3 done-criterion) — and the
        buffers stay O(E*C*d), not O(S*E*C)."""
        x = jnp.asarray(np.random.RandomState(7).randn(2, 128, 32),
                        jnp.float32)
        layer = MoeMlp(num_experts=32, hidden_dim=64, top_k=2)
        params = layer.init(jax.random.PRNGKey(1), x)
        y, aux = layer.apply(params, x, mutable=["losses"])
        assert y.shape == x.shape
        assert np.isfinite(np.asarray(y)).all()
        assert np.isfinite(float(aux["losses"]["moe_aux"][0]))
