"""serving/speculative.py + prefix-resident admission (ISSUE 19).

Pins, in order:
* SpeculativeEngine validation: int8 pools refused, spec_k >= 1, the
  draft's vocab and position table must fit, and the scheduler refuses
  plain SlotEngines;
* the tentpole exactness pin: the speculative stream is BITWISE the
  non-speculative SlotEngine's (and the solo full-context greedy
  forward's) across accept/reject mixes, mixed temperatures, per-request
  seeds, and slot churn — with zero recompiles after warmup;
* a same-weights "oracle" draft accepts nearly everything and finishes
  in far fewer verify rounds than emitted tokens (the perf mechanism,
  pinned structurally rather than by wall clock);
* prefix-resident admission: a fully-resident prompt admits with ZERO
  prefill dispatch (span census: `prefill_skip`, no `prefill`), partial
  residency prefills only the tail — both bitwise vs the cold path, on
  the plain AND the speculative engine; the fp32-only / opt-out gates;
* draft-pool pressure: admission throttles when the draft pool cannot
  hold a request (target lease rolled back, request stays pending) and
  every request still completes bitwise with nothing leaked;
* the ``serving_spec`` contract + `spec-verify-donated` rule,
  mutation-tested per the checker's own standard (the n_emit side
  output must cost the alias table nothing);
* router mid-POST death: a replica dying mid-response (truncated body or
  chunk-boundary IncompleteRead) surfaces as ReplicaDead immediately and
  the seed-pinned resubmit emits on a survivor — clean under
  DPT_LOCKCHECK=1.
"""

import dataclasses as dc
import http.client
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_training_tpu import telemetry
from distributed_pytorch_training_tpu.models.gpt2 import GPT2LMHead
from distributed_pytorch_training_tpu.serving.batching import RequestQueue
from distributed_pytorch_training_tpu.serving.continuous import (
    ContinuousScheduler, SlotEngine,
)
from distributed_pytorch_training_tpu.serving.paged import (
    PagedServeConfig, PagePool,
)
from distributed_pytorch_training_tpu.serving.router import (
    HttpReplica, InProcessReplica, ReplicaDead, Router,
)
from distributed_pytorch_training_tpu.serving.speculative import (
    SpeculativeEngine, SpeculativeScheduler,
)
from distributed_pytorch_training_tpu.utils import locktrace

VOCAB = 97
SPEC_K = 3


def tiny_model(**kw):
    cfg = dict(vocab_size=VOCAB, hidden_dim=32, depth=2, num_heads=2,
               max_position=64)
    cfg.update(kw)
    return GPT2LMHead(**cfg)


def paged_cfg(**kw):
    cfg = dict(buckets=(8, 16), rows=8, max_new_tokens=6, page_size=4)
    cfg.update(kw)
    return PagedServeConfig(**cfg)


@pytest.fixture(scope="module")
def tiny(mesh8):
    model = tiny_model()
    params = model.init(jax.random.PRNGKey(0), np.zeros((1, 8), np.int32),
                        train=False)["params"]
    return model, params


@pytest.fixture(scope="module")
def draft_tiny():
    """A structurally SMALLER draft (1 block, hidden 16) with its own
    random init: its greedy proposals agree with the target's sampled
    stream only sometimes, which is exactly the mixed accept/reject
    regime the bitwise pin must survive."""
    model = tiny_model(hidden_dim=16, depth=1, num_heads=2)
    params = model.init(jax.random.PRNGKey(7), np.zeros((1, 8), np.int32),
                        train=False)["params"]
    return model, params


@pytest.fixture(scope="module")
def spec_engine(mesh8, tiny, draft_tiny):
    model, params = tiny
    dmodel, dparams = draft_tiny
    eng = SpeculativeEngine(model, mesh8, paged_cfg(), params, dmodel,
                            dparams, spec_k=SPEC_K)
    eng.warmup()
    return eng


@pytest.fixture(scope="module")
def plain_engine(mesh8, tiny):
    model, params = tiny
    eng = SlotEngine(model, mesh8, paged_cfg(), params)
    eng.warmup()
    return eng


def prompts(ns, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, VOCAB, n).astype(np.int32) for n in ns]


_REF_PAD = 32          # >= longest prompt (16) + max_new_tokens (6)
_ref_fwd_cache: dict = {}


def ref_greedy(model, params, prompt, n):
    """The solo reference (test_continuous.py's bitwise anchor): greedy
    continuation off one fixed-pad jitted full-context forward."""
    fwd = _ref_fwd_cache.get(id(model))
    if fwd is None:
        fwd = jax.jit(lambda p, ids: model.apply({"params": p}, ids,
                                                 train=False))
        _ref_fwd_cache[id(model)] = fwd
    ids = np.zeros((1, _REF_PAD), np.int32)
    ids[0, :len(prompt)] = prompt
    cur = len(prompt)
    out = []
    for _ in range(n):
        logits = fwd(params, jnp.asarray(ids))
        nxt = int(jnp.argmax(logits[0, cur - 1]))
        out.append(nxt)
        ids[0, cur] = nxt
        cur += 1
    return np.asarray(out, np.int32)


def serve_all(engine, specs, scheduler_cls=None, timeout=300.0):
    """Reset the engine, push every spec through a fresh scheduler,
    drain, and return (scheduler, per-request Results in order)."""
    if scheduler_cls is None:
        scheduler_cls = (SpeculativeScheduler
                         if isinstance(engine, SpeculativeEngine)
                         else ContinuousScheduler)
    engine.reset_state()
    q = RequestQueue(engine.config.buckets)
    sched = scheduler_cls(engine, q)
    reqs = [q.submit(toks, **kw) for toks, kw in specs]
    sched.drain()
    return sched, [r.result(timeout=timeout) for r in reqs]


# ---------------------------------------------------------------------------
# Constructor validation: the exactness gates
# ---------------------------------------------------------------------------


class TestSpecValidation:
    def test_int8_pool_refused(self, mesh8, tiny, draft_tiny):
        model, params = tiny
        dmodel, dparams = draft_tiny
        with pytest.raises(ValueError, match="fp32"):
            SpeculativeEngine(model, mesh8, paged_cfg(kv_dtype="int8"),
                              params, dmodel, dparams, spec_k=SPEC_K)

    def test_spec_k_floor(self, mesh8, tiny, draft_tiny):
        model, params = tiny
        dmodel, dparams = draft_tiny
        with pytest.raises(ValueError, match="spec_k"):
            SpeculativeEngine(model, mesh8, paged_cfg(), params, dmodel,
                              dparams, spec_k=0)

    def test_vocab_mismatch_refused(self, mesh8, tiny):
        model, params = tiny
        dmodel = tiny_model(vocab_size=31, hidden_dim=16, depth=1)
        dparams = dmodel.init(jax.random.PRNGKey(1),
                              np.zeros((1, 8), np.int32),
                              train=False)["params"]
        with pytest.raises(ValueError, match="vocab"):
            SpeculativeEngine(model, mesh8, paged_cfg(), params, dmodel,
                              dparams, spec_k=SPEC_K)

    def test_scheduler_refuses_plain_engine(self, plain_engine):
        q = RequestQueue(plain_engine.config.buckets)
        with pytest.raises(ValueError, match="SpeculativeEngine"):
            SpeculativeScheduler(plain_engine, q)


# ---------------------------------------------------------------------------
# The tentpole pin: bitwise parity vs the non-speculative path
# ---------------------------------------------------------------------------


class TestSpecBitwiseParity:
    def test_greedy_matches_solo_forward_bitwise(self, spec_engine, tiny):
        model, params = tiny
        seqs = prompts((3, 8, 11, 16, 5, 13), seed=1)
        _, res = serve_all(spec_engine,
                           [(s, dict(temperature=0.0)) for s in seqs])
        for i, (s, r) in enumerate(zip(seqs, res)):
            np.testing.assert_array_equal(
                r.tokens, ref_greedy(model, params, s, 6),
                err_msg=f"request {i} (len {len(s)})")

    def test_mixed_temps_and_churn_match_plain_engine(self, spec_engine,
                                                      plain_engine):
        """12 requests over 8 rows (churn), mixed temperatures / top_p /
        per-request seeds and wants: every stream bitwise identical to
        the plain SlotEngine's under the plain scheduler. Acceptance is
        exact match, so the draft's numerics cannot leak into the output
        — this is the PARITY.md clause as an assertion."""
        rng = np.random.RandomState(3)
        seqs = prompts([int(rng.randint(1, 17)) for _ in range(12)],
                       seed=4)
        kws = [dict(temperature=float(rng.choice([0.0, 0.7, 1.0])),
                    top_p=float(rng.choice([0.9, 1.0])),
                    seed=int(100 + i),
                    max_new_tokens=int(rng.randint(1, 7)))
               for i in range(12)]
        specs = list(zip(seqs, kws))
        sched, spec_res = serve_all(spec_engine, specs)
        _, plain_res = serve_all(plain_engine, specs)
        assert sched.spec_rounds > 0 and sched.spec_proposed > 0
        for i, (a, b) in enumerate(zip(spec_res, plain_res)):
            np.testing.assert_array_equal(
                a.tokens, b.tokens,
                err_msg=f"request {i}: speculative stream diverged "
                        f"(kw {kws[i]})")

    def test_zero_recompiles_after_warmup(self, spec_engine):
        rng = np.random.RandomState(5)
        before = spec_engine.compiles
        specs = [(rng.randint(0, VOCAB, int(rng.randint(1, 17)))
                  .astype(np.int32),
                  dict(temperature=0.0,
                       max_new_tokens=int(rng.randint(1, 7))))
                 for _ in range(20)]
        sched, res = serve_all(spec_engine, specs)
        assert len(res) == 20 and all(r.tokens.size for r in res)
        assert spec_engine.compiles == before, \
            "a draft/verify round recompiled after warmup"

    # slow tier: the oracle leg builds (and warms up) a THIRD engine just
    # to prove the acceptance machinery can accept — a quality
    # diagnostic, not a correctness pin; the bitwise-parity tests above
    # are the tier-1 story and hold at ANY accept ratio
    @pytest.mark.slow
    def test_oracle_draft_accepts_and_cuts_rounds(self, mesh8, tiny):
        """Draft == target: greedy proposals are the target's own argmax
        stream, so (temperature 0) every round accepts the full window.
        Pins the accept accounting AND the perf mechanism structurally:
        emitting `want` tokens takes ~want/(K+1) verify rounds, not
        `want` decode steps."""
        model, params = tiny
        eng = SpeculativeEngine(model, mesh8,
                                paged_cfg(buckets=(16,), rows=2), params,
                                model, params, spec_k=SPEC_K)
        sched, res = serve_all(
            eng, [(p, dict(temperature=0.0))
                  for p in prompts((9, 14), seed=6)])
        for p, r in zip(prompts((9, 14), seed=6), res):
            np.testing.assert_array_equal(
                r.tokens, ref_greedy(model, params, p, 6))
        # 2 requests x 6 tokens over K+1=4-token rounds: far fewer verify
        # rounds than the 12 per-token steps the plain path would fence
        assert sched.spec_rounds <= 6
        assert sched.accept_ratio >= 0.5, (
            f"oracle draft accept ratio {sched.accept_ratio:.3f} — the "
            "draft cache is starving (the K+1th propose write regressed?)")


# ---------------------------------------------------------------------------
# Prefix-resident admission: skip / resume, census + bitwise
# ---------------------------------------------------------------------------


class TestPrefixResidentAdmission:
    def _serve_seq(self, engine, prompt_list):
        """Serve prompts SEQUENTIALLY through one replica worker (each
        result awaited before the next submit) so later prompts see the
        residency earlier ones registered. Returns (scheduler, results,
        telemetry events)."""
        engine.reset_state()
        rec = telemetry.configure()          # ring-only stream
        try:
            replica = InProcessReplica("r0", engine)
            results = [replica.submit(p, temperature=0.0)
                       .result(timeout=120.0) for p in prompt_list]
            replica.stop()
            events = rec.tail(10_000)
        finally:
            telemetry.reset()
        return replica.scheduler, results, events

    @staticmethod
    def _spans(events, name):
        return [e for e in events
                if e["kind"] == "span" and e["name"] == name]

    def test_fully_resident_skips_prefill_bitwise(self, plain_engine,
                                                  tiny):
        """The zero-prefill census: an identical page-aligned prompt,
        served twice — the second admission dispatches NO prefill (span
        census), and both streams are bitwise the solo forward's."""
        model, params = tiny
        (p,) = prompts((16,), seed=8)        # 16 = 4 full pages
        sched, res, events = self._serve_seq(plain_engine, [p, p])
        assert sched.prefill_skips == 1 and sched.tail_resumes == 0
        assert len(self._spans(events, "prefill")) == 1   # the cold one
        assert len(self._spans(events, "prefill_skip")) == 1
        ref = ref_greedy(model, params, p, 6)
        for r in res:
            np.testing.assert_array_equal(r.tokens, ref)

    def test_partial_residency_prefills_tail_only_bitwise(
            self, plain_engine, tiny):
        model, params = tiny
        rng = np.random.RandomState(9)
        base = rng.randint(0, VOCAB, 8).astype(np.int32)   # 2 full pages
        ext = np.concatenate([base,
                              rng.randint(0, VOCAB, 5).astype(np.int32)])
        sched, res, _ = self._serve_seq(plain_engine, [base, ext])
        assert sched.tail_resumes == 1 and sched.prefill_skips == 0
        np.testing.assert_array_equal(res[0].tokens,
                                      ref_greedy(model, params, base, 6))
        np.testing.assert_array_equal(res[1].tokens,
                                      ref_greedy(model, params, ext, 6))

    def test_skip_composes_with_speculation_bitwise(self, spec_engine,
                                                    tiny):
        """Both tentpole halves at once: the second identical prompt
        skip-admits INTO the speculative round loop (last-prompt logits
        captured off verify window row 0) and still emits the bitwise
        stream."""
        model, params = tiny
        (p,) = prompts((16,), seed=10)
        sched, res, events = self._serve_seq(spec_engine, [p, p])
        assert sched.prefill_skips == 1
        assert len(self._spans(events, "prefill")) == 1
        assert sched.spec_rounds > 0
        ref = ref_greedy(model, params, p, 6)
        for r in res:
            np.testing.assert_array_equal(r.tokens, ref)
            # the skip admission's last-prompt logits (captured off
            # verify window row 0 via the last_pos protocol) must agree
            # with the stream: token #0 is their argmax under greedy
            assert int(np.argmax(r.last_logits)) == int(r.tokens[0])

    def test_gates_disable_the_fast_path(self, mesh8, tiny):
        """The exactness gates: int8 pools and prefix_sharing=False turn
        prefix skip OFF (construction only — no compile); an explicit
        prefix_skip=False opts out while shared pages keep deduping."""
        model, params = tiny
        assert SlotEngine(model, mesh8, paged_cfg(kv_dtype="int8"),
                          params).prefix_skip_enabled is False
        assert SlotEngine(model, mesh8, paged_cfg(prefix_sharing=False),
                          params).prefix_skip_enabled is False
        assert SlotEngine(model, mesh8, paged_cfg(prefix_skip=False),
                          params).prefix_skip_enabled is False
        assert SlotEngine(model, mesh8, paged_cfg(),
                          params).prefix_skip_enabled is True

    # slow tier: the opt-out leg builds its own engine just to prove the
    # escape hatch is cosmetic; the gates test above pins the flag
    # plumbing cheaply and the skip-path parity legs are the tier-1 story
    @pytest.mark.slow
    def test_opt_out_still_bitwise_with_full_prefill(self, mesh8, tiny):
        """prefix_skip=False serves the identical prompt twice through
        TWO full prefills (census: zero skips) and the stream is still
        bitwise — the fast path is an optimization, not a semantic."""
        model, params = tiny
        eng = SlotEngine(model, mesh8,
                         paged_cfg(buckets=(16,), rows=2,
                                   prefix_skip=False), params)
        (p,) = prompts((16,), seed=8)
        sched, res, events = self._serve_seq(eng, [p, p])
        assert sched.prefill_skips == 0 and sched.tail_resumes == 0
        assert len(self._spans(events, "prefill")) == 2
        ref = ref_greedy(model, params, p, 6)
        for r in res:
            np.testing.assert_array_equal(r.tokens, ref)


# ---------------------------------------------------------------------------
# Draft-pool pressure: throttle, never deadlock, never leak
# ---------------------------------------------------------------------------


class TestDraftPoolPressure:
    def test_exhausted_draft_pool_throttles_and_completes(self,
                                                          spec_engine,
                                                          tiny,
                                                          monkeypatch):
        """Shrink the draft allocator to two slots' worth: admissions
        past that fail the draft lease, roll the TARGET lease back, and
        park the request pending — every request still completes bitwise
        and the draft pool drains to its starting free count (nothing
        leaked through the rollback path). DPT_LOCKCHECK=1 is armed so
        the traced acquisition order must stay clean."""
        monkeypatch.setenv("DPT_LOCKCHECK", "1")
        locktrace.trace().reset()
        model, params = tiny
        spec_engine.reset_state()
        q = RequestQueue(spec_engine.config.buckets)
        sched = SpeculativeScheduler(spec_engine, q)
        dcfg = spec_engine.draft_config
        sched.draft_pool = PagePool(2 * dcfg.pages_per_slot + 1,
                                    dcfg.page_size, dcfg.pages_per_slot,
                                    prefix_sharing=False)
        free0 = sched.draft_pool.free_pages()
        seqs = prompts((5, 9, 13, 7, 11, 6), seed=21)
        reqs = [q.submit(s, temperature=0.0) for s in seqs]
        sched.drain()
        res = [r.result(timeout=300.0) for r in reqs]
        for i, (s, r) in enumerate(zip(seqs, res)):
            np.testing.assert_array_equal(
                r.tokens, ref_greedy(model, params, s, 6),
                err_msg=f"request {i} (len {len(s)})")
        assert sched.draft_pool.free_pages() == free0
        assert locktrace.cross_check() == []


# ---------------------------------------------------------------------------
# The serving_spec contract + spec-verify-donated rule (mutation-tested)
# ---------------------------------------------------------------------------


class TestSpecContract:
    # the registered-contract evaluator itself (get_contract +
    # evaluate_contract) runs in the full-matrix CLI acceptance test —
    # re-evaluating it here would pay a second engine build + verify
    # compile for no new coverage; this leg pins the census and the
    # rule on the LIVE warmed engine instead
    def test_live_engine_artifacts_pass(self, spec_engine):
        from distributed_pytorch_training_tpu.analysis.hlo_rules import (
            check_artifacts, spec_serving_artifacts,
        )

        artifacts = spec_serving_artifacts(spec_engine)
        # fp32 pool (2 layer-stacked leaves) + every slot-control leaf:
        # the n_emit side output must not cost an alias entry
        assert artifacts.config["spec_cache_leaves"] == 12
        assert (artifacts.config["spec_cache_leaves"]
                == 2 + len(spec_engine._control))
        assert check_artifacts(artifacts) == []

    def test_mutation_missing_alias_entries_flag(self):
        from distributed_pytorch_training_tpu.analysis.hlo_rules import (
            StepArtifacts, check_artifacts,
        )

        partial = StepArtifacts(
            name="mut", optimized_text=(
                "HloModule spec, input_output_alias={ {0}: (1, {}, "
                "may-alias) }, entry_computation_layout={()}"),
            config={"serving_spec": True, "donate_state": True,
                    "spec_cache_leaves": 12})
        found = check_artifacts(partial, rules=["spec-verify-donated"])
        assert len(found) == 1 and "1 of the >= 12" in found[0].message
        absent = StepArtifacts(
            name="mut2", optimized_text="HloModule spec",
            config={"serving_spec": True, "donate_state": True,
                    "spec_cache_leaves": 12})
        assert check_artifacts(absent, rules=["spec-verify-donated"])
        # non-spec configs are out of scope — the rule stays silent
        plain = StepArtifacts(name="t", optimized_text="HloModule x",
                              config={"donate_state": True})
        assert check_artifacts(plain, rules=["spec-verify-donated"]) == []

    def test_mutation_dropped_leaf_flags_on_real_lowering(self,
                                                          spec_engine):
        from distributed_pytorch_training_tpu.analysis.hlo_rules import (
            check_artifacts, spec_serving_artifacts,
        )

        artifacts = spec_serving_artifacts(spec_engine)
        poisoned = dc.replace(
            artifacts, config={**artifacts.config,
                               "spec_cache_leaves":
                               artifacts.config["spec_cache_leaves"]
                               + 100})
        found = check_artifacts(poisoned, rules=["spec-verify-donated"])
        assert len(found) == 1


# ---------------------------------------------------------------------------
# Router mid-POST death: half a response is a death, retries are bitwise
# ---------------------------------------------------------------------------


class _FakeResp:
    """A urlopen context manager serving a scripted body."""

    status = 200

    def __init__(self, chunks, content_length=None, raise_mid=False):
        self._chunks = list(chunks)
        self.headers = ({"Content-Length": str(content_length)}
                        if content_length is not None else {})
        self._raise_mid = raise_mid

    def read(self, n):
        if not self._chunks:
            if self._raise_mid:
                raise http.client.IncompleteRead(b"", 64)
            return b""
        return self._chunks.pop(0)

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


class _StubPending:
    def __init__(self, replica):
        self.replica = replica

    def result(self, timeout=None):
        from distributed_pytorch_training_tpu.serving.batching import (
            Result,
        )

        return Result(tokens=np.arange(3, dtype=np.int32),
                      last_logits=np.zeros(VOCAB, np.float32))


class _StubReplica:
    def __init__(self, name, depth=0):
        self.name = name
        self.depth = depth
        self.submits = []

    def healthy(self):
        return True

    def queue_depth(self):
        return self.depth

    def submit(self, tokens, **kw):
        self.submits.append(kw)
        return _StubPending(self)


class TestRouterMidPostDeath:
    def test_truncated_body_is_replica_dead(self, monkeypatch):
        """A clean close short of Content-Length is half a response: the
        incremental read promotes it to IncompleteRead -> ReplicaDead,
        NOT a json decode error at the request timeout."""
        import urllib.request as _ur

        replica = HttpReplica("h", port=1)
        monkeypatch.setattr(
            _ur, "urlopen",
            lambda *a, **kw: _FakeResp([b'{"tokens": [1, 2'],
                                       content_length=4096))
        with pytest.raises(ReplicaDead, match="died mid-response"):
            replica.submit(np.ones(3, np.int32)).result(timeout=1.0)
        assert not replica.healthy()

    def test_chunk_boundary_death_is_replica_dead(self, monkeypatch):
        """The socket tears mid-read (http.client raises IncompleteRead
        itself): same verdict, same immediacy."""
        import urllib.request as _ur

        replica = HttpReplica("h", port=1)
        monkeypatch.setattr(
            _ur, "urlopen",
            lambda *a, **kw: _FakeResp([b'{"tok'], content_length=4096,
                                       raise_mid=True))
        with pytest.raises(ReplicaDead, match="died mid-response"):
            replica.submit(np.ones(3, np.int32)).result(timeout=1.0)
        assert not replica.healthy()

    def test_mid_post_death_reroutes_with_pinned_seed(self, monkeypatch):
        """The regression drill: replica dies mid-POST, the router
        resubmits to a survivor WITH THE ROUTE-TIME SEED (the retry
        emits the identical stream — sampling is a function of (request,
        seed) alone). Runs under DPT_LOCKCHECK=1: the traced lock order
        across router + queue locks must stay clean."""
        import urllib.request as _ur

        monkeypatch.setenv("DPT_LOCKCHECK", "1")
        locktrace.trace().reset()
        dying = HttpReplica("h", port=1)
        survivor = _StubReplica("s", depth=1)   # depth: h wins dispatch
        monkeypatch.setattr(
            _ur, "urlopen",
            lambda *a, **kw: _FakeResp([b'{"tokens": [9'],
                                       content_length=4096))
        router = Router([dying, survivor])
        req = router.submit(np.ones(4, np.int32))
        assert req.replica_name == "h"
        seed = req.kw["seed"]
        res = req.result(timeout=5.0)
        assert req.replica_deaths == 1 and req.replica_name == "s"
        assert survivor.submits[-1]["seed"] == seed
        np.testing.assert_array_equal(res.tokens,
                                      np.arange(3, dtype=np.int32))
        assert locktrace.cross_check() == []


# ---------------------------------------------------------------------------
# The CLI bench arm with --draft + --shared-frac (slow: subprocess e2e)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_cli_bench_draft_and_shared_frac(tmp_path):
    """`serving bench --continuous --draft ... --shared-frac 0.5` runs
    the speculative + prefix-skip row end to end, reports accept_ratio
    and the warm/cold TTFT split, and exits 0 iff
    recompiles_after_warmup == 0 (the same hard gate as the plain arm)."""
    import json
    import os

    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    out = subprocess.run(
        [sys.executable, "-m",
         "distributed_pytorch_training_tpu.serving", "bench",
         "--continuous", "--json",
         "--model", "gpt2_124m",
         "--model-overrides",
         "vocab_size=64,hidden_dim=32,depth=2,num_heads=2",
         "--draft", "gpt2_124m", "--draft-k", "3",
         "--shared-frac", "0.5",
         "--buckets", "8,16", "--rows", "4", "--max-new-tokens", "4",
         "--requests", "10", "--offered-load", "32",
         "--output-dir", str(tmp_path / "out")],
        env=env, cwd=str(Path(__file__).resolve().parent.parent),
        capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stdout + out.stderr
    row = json.loads(out.stdout.strip().splitlines()[-1])
    assert row["draft"] == "gpt2_124m" and row["spec_rounds"] > 0
    assert "accept_ratio" in row and "accepted_per_verify" in row
    assert row["prefill_skips"] >= 1
    assert "ttft_warm_p50_ms" in row and "ttft_cold_p50_ms" in row
    assert row["recompiles_after_warmup"] == 0
