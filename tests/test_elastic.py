"""Elastic data parallelism (ISSUE 11): the N->M reshard helpers, the
world-size-aware checkpoint manifest, and the state-level reshard across
real layouts.

The binding contracts:
* `reshard_flat_padded` re-chunks old-N flat-padded leaves to new-M
  padding EXACTLY (round trips, pad recomputed, nonzero-tail loud);
* `fold_ef_rows` preserves the telescoping column-wise EF total;
* a zero1 / fsdp-explicit TrainState trained at world 8 reshards to a
  world-4 template value-exactly (flat leaves re-slice, EF rows fold) and
  the world-4 trainer runs on it;
* checkpoint manifests record the world size, `restore_latest` builds
  per-label templates from it (`template_factory`) and a genuine world
  mismatch is `CheckpointWorldSizeMismatch` naming both sizes.

(The supervised end-to-end resize + bitwise post-resize parity lives in
tests/test_resilience.py / the `resilience chaos --elastic` harness.)
"""

import numpy as np
import pytest

import jax

from distributed_pytorch_training_tpu.parallel.grad_sync import (
    BucketPlan, build_layer_plan, fold_ef_rows, padded_bucket_bounds,
    reshard_fsdp_ef_row, reshard_multihop_ef_row,
)
from distributed_pytorch_training_tpu.parallel.mesh import batch_shard_count
from distributed_pytorch_training_tpu.parallel.sharding import (
    flat_padded_size, reshard_flat_padded, reshard_flat_tree,
)
from distributed_pytorch_training_tpu.resilience.elastic import (
    plan_elastic_world, reshard_train_state,
)

GLOBAL_BATCH = 16


# ---------------------------------------------------------------------------
# host-side helpers (no device work)
# ---------------------------------------------------------------------------


class TestReshardFlatPadded:
    @pytest.mark.parametrize("true_size", [1, 5, 6, 9, 16, 1000])
    @pytest.mark.parametrize("old_n,new_n", [(8, 4), (4, 8), (8, 3),
                                             (3, 8), (2, 2)])
    def test_rechunk_matches_direct_padding(self, true_size, old_n, new_n):
        """old-N -> new-M re-slice == padding the true content directly at
        M (the padding is recomputed, the content untouched)."""
        content = np.arange(1, true_size + 1, dtype=np.float32)
        old = np.pad(content, (0, flat_padded_size(true_size, old_n)
                               - true_size))
        new = reshard_flat_padded(old, flat_padded_size(true_size, new_n))
        expect = np.pad(content, (0, flat_padded_size(true_size, new_n)
                                  - true_size))
        np.testing.assert_array_equal(new, expect)

    @pytest.mark.parametrize("true_size,old_n,new_n",
                             [(5, 8, 4), (9, 4, 8), (1000, 8, 2)])
    def test_round_trip_is_exact(self, true_size, old_n, new_n):
        content = np.random.RandomState(0).randn(true_size).astype(
            np.float32)
        old = np.pad(content, (0, flat_padded_size(true_size, old_n)
                               - true_size))
        there = reshard_flat_padded(old,
                                    flat_padded_size(true_size, new_n))
        back = reshard_flat_padded(there,
                                   flat_padded_size(true_size, old_n))
        np.testing.assert_array_equal(back, old)

    def test_grow_with_smaller_total_padding(self):
        """ISSUE-12 satellite: the grow direction where the new PADDED
        length is SMALLER than the old one — true size 9 at old world 8
        pads to 16 (2/shard), but at new world 3 pads to only 9
        (3/shard): growing the per-shard chunk SHRINKS the total, and the
        re-slice must truncate exactly the 7 pad zeros, no more."""
        content = np.arange(1, 10, dtype=np.float32)          # true 9
        old = np.pad(content, (0, flat_padded_size(9, 8) - 9))  # len 16
        assert old.shape == (16,)
        new = reshard_flat_padded(old, flat_padded_size(9, 3))  # len 9
        assert new.shape == (9,)
        np.testing.assert_array_equal(new, content)
        # and the mirror: 3 -> 8 re-pads with zeros, content untouched
        back = reshard_flat_padded(new, flat_padded_size(9, 8))
        np.testing.assert_array_equal(back, old)

    def test_grow_truncation_still_guards_content(self):
        """Same shape transition, but with real content smuggled into
        what should be the pad region — the truncating grow must refuse
        as loudly as a shrink does."""
        bad = np.arange(1, 17, dtype=np.float32)  # nonzero through 16
        with pytest.raises(ValueError, match="NONZERO tail"):
            reshard_flat_padded(bad, 9)

    def test_nonzero_tail_is_loud(self):
        """Shrinking must refuse to drop real content — a nonzero tail
        means the input was never a zero-padded flat layout."""
        bad = np.ones(8, np.float32)  # "pad" region holds content
        with pytest.raises(ValueError, match="NONZERO tail"):
            reshard_flat_padded(bad, 4)

    def test_non_1d_is_loud(self):
        with pytest.raises(ValueError, match="1-D"):
            reshard_flat_padded(np.zeros((2, 4), np.float32), 8)

    def test_tree_passthrough_and_rechunk(self):
        old = {"w": np.arange(6, dtype=np.float32),  # model-shaped: equal
               "flat": np.pad(np.arange(1, 6, dtype=np.float32), (0, 3))}
        tmpl = {"w": np.zeros(6, np.float32),
                "flat": np.zeros(8, np.float32)}  # same padded size at M
        out = reshard_flat_tree(old, tmpl)
        np.testing.assert_array_equal(out["w"], old["w"])
        np.testing.assert_array_equal(out["flat"], old["flat"])
        with pytest.raises(ValueError, match="only flat-padded 1-D"):
            reshard_flat_tree({"x": np.zeros((2, 3), np.float32)},
                              {"x": np.zeros((3, 2), np.float32)})


class TestFoldEfRows:
    def test_fold_preserves_column_totals(self):
        rows = np.random.RandomState(1).randn(8, 12).astype(np.float64)
        folded = fold_ef_rows(rows, 4)
        assert folded.shape == (4, 12)
        # new row m = exact fp sum of old rows {m, m+4} (float64: exact
        # enough to compare against np's own pairwise order here)
        for m in range(4):
            np.testing.assert_array_equal(folded[m], rows[m] + rows[m + 4])

    def test_grow_pads_zero_rows(self):
        rows = np.ones((2, 5), np.float32)
        grown = fold_ef_rows(rows, 4)
        np.testing.assert_array_equal(grown[:2], rows)
        assert not grown[2:].any()

    def test_grow_with_nonzero_residuals_preserves_totals(self):
        """ISSUE-12 satellite: M -> N grow with NONZERO residual rows —
        the returning replicas join with zero carried error while the
        survivors keep theirs bit-for-bit, so the telescoping column
        total is preserved exactly (what re-enters the next reduction)."""
        rows = np.random.RandomState(3).randn(4, 9).astype(np.float32)
        grown = fold_ef_rows(rows, 8)
        assert grown.shape == (8, 9)
        np.testing.assert_array_equal(grown[:4], rows)  # survivors exact
        assert not grown[4:].any()                      # newcomers zero
        np.testing.assert_array_equal(grown.sum(axis=0, dtype=np.float64),
                                      rows.sum(axis=0, dtype=np.float64))

    def test_non_divisor_fold_both_directions(self):
        """8 -> 3 folds rows {m, m+3, m+6}; 3 -> 8 zero-extends — the
        fold never requires the worlds to divide each other."""
        rows = np.random.RandomState(4).randn(8, 6).astype(np.float64)
        down = fold_ef_rows(rows, 3)
        for m in range(3):
            expect = np.zeros(6)
            for i in range(m, 8, 3):
                expect = expect + rows[i]
            np.testing.assert_array_equal(down[m], expect)
        up = fold_ef_rows(down, 8)
        np.testing.assert_array_equal(up[:3], down)
        assert not up[3:].any()


class TestMultihopAndFsdpRowReshard:
    def test_multihop_row_rechunks_per_bucket(self):
        plan = BucketPlan(total_size=10, bounds=(0, 6, 10))
        old_n, new_n = 4, 2
        old_b = padded_bucket_bounds(plan, old_n)   # buckets padded to 4
        new_b = padded_bucket_bounds(plan, new_n)   # buckets padded to 2
        row = np.zeros(old_b[-1], np.float32)
        # fill ONLY the true region of each bucket (pad slots stay 0 —
        # the codec invariant the reshard relies on)
        sizes = plan.bucket_sizes()
        for k, (a, size) in enumerate(zip(old_b, sizes)):
            row[a:a + size] = np.arange(1, size + 1) + 100 * k
        new = reshard_multihop_ef_row(row, plan, old_n, new_n)
        assert new.shape == (new_b[-1],)
        for k, (a, na, size) in enumerate(zip(old_b, new_b, sizes)):
            np.testing.assert_array_equal(new[na:na + size],
                                          row[a:a + size])
        # and back — exact
        back = reshard_multihop_ef_row(new, plan, new_n, old_n)
        np.testing.assert_array_equal(back, row)

    def test_fsdp_group_row_rechunks_per_leaf(self):
        # two leaves of sizes 5 and 9 in ONE group (grouping is by the
        # TOP-level key — nest them under one module), worlds 4 -> 2
        params = {"layer": {"a": np.zeros(5), "b": np.zeros(9)}}
        old_plan = build_layer_plan(params, 4)
        new_plan = build_layer_plan(params, 2)
        (og,), (ng,) = old_plan.groups, new_plan.groups
        row = np.zeros(4 * og.row_size, np.float32)
        mat = row.reshape(4, og.row_size)
        off = 0
        leaf_values = {}
        for slot, (name, size) in enumerate((("a", 5), ("b", 9))):
            c = og.chunk_sizes[slot]
            flat = np.zeros(4 * c, np.float32)
            flat[:size] = np.arange(1, size + 1) + 100 * slot
            leaf_values[name] = flat[:size]
            mat[:, off:off + c] = flat.reshape(4, c)
            off += c
        new = reshard_fsdp_ef_row(row, og, ng, 4, 2)
        nmat = new.reshape(2, ng.row_size)
        off = 0
        for slot, (name, size) in enumerate((("a", 5), ("b", 9))):
            c = ng.chunk_sizes[slot]
            flat = np.ascontiguousarray(nmat[:, off:off + c]).reshape(-1)
            np.testing.assert_array_equal(flat[:size], leaf_values[name])
            assert not flat[size:].any()
            off += c
        back = reshard_fsdp_ef_row(new, ng, og, 2, 4)
        np.testing.assert_array_equal(back, row)


class TestPlanElasticWorld:
    def test_largest_feasible_divisor(self):
        assert plan_elastic_world(7, 16) == 4   # 7,6,5 do not divide 16
        assert plan_elastic_world(8, 16) == 8
        assert plan_elastic_world(3, 16) == 2
        assert plan_elastic_world(1, 16) == 1
        assert plan_elastic_world(5, 15) == 5
        assert plan_elastic_world(100, 16) == 16  # never above the batch

    def test_no_survivors_is_loud(self):
        with pytest.raises(ValueError, match="surviving"):
            plan_elastic_world(0, 16)


# ---------------------------------------------------------------------------
# state-level reshard across real layouts (the chaos CLI's rig)
# ---------------------------------------------------------------------------


def _rig(mesh, layout, wire):
    from distributed_pytorch_training_tpu.resilience.__main__ import (
        _build_rig,
    )

    return _build_rig(mesh, seed=0, dataset_size=32,
                      per_device_batch=GLOBAL_BATCH
                      // batch_shard_count(mesh),
                      layout=layout, wire_dtype=wire)


@pytest.fixture(scope="module")
def mesh4(devices):
    from distributed_pytorch_training_tpu.parallel import (
        MeshSpec, build_mesh,
    )

    return build_mesh(MeshSpec(data=4), devices=devices[:4])


def _flat_leaves_match(old_tree, new_tree):
    """Every pair: same-shape leaves bitwise equal; 1-D padded leaves
    re-sliced (new == old's prefix, the rest was zeros)."""
    for old, new in zip(jax.tree_util.tree_leaves(old_tree),
                        jax.tree_util.tree_leaves(new_tree)):
        o = np.asarray(jax.device_get(old))
        n = np.asarray(jax.device_get(new))
        if o.shape == n.shape:
            np.testing.assert_array_equal(o, n)
        else:
            assert o.ndim == n.ndim == 1 and n.size <= o.size
            np.testing.assert_array_equal(n, o[:n.size])
            assert not o[n.size:].any()  # only pad zeros were dropped


class TestReshardTrainState:
    @pytest.mark.slow  # ~30 s (two trainer compiles); EF-row reshard exactness is pinned fast by the fsdp-int8 leg, zero1 CLI parity by the chaos suite
    def test_zero1_int8_state_reshards_exactly(self, mesh8, mesh4):
        """The richest zero1 state (flat-padded moments + per-leaf EF
        residual rows) trained at world 8 reshards to the world-4 template
        value-exactly, and the world-4 trainer trains on it."""
        t8, sf8, l8 = _rig(mesh8, "zero1", "int8")
        state = sf8()
        state, *_ = t8.train_epoch(state, l8.epoch(0), 0, len(l8))
        t4, sf4, l4 = _rig(mesh4, "zero1", "int8")
        new = reshard_train_state(state, 8, 4, t4, sf4())

        assert int(new.step) == int(state.step)
        _flat_leaves_match(state.params, new.params)        # replicated
        _flat_leaves_match(state.batch_stats, new.batch_stats)
        _flat_leaves_match(state.opt_state, new.opt_state)  # re-sliced
        # EF rows fold: new row m is exactly old row m + old row m+4,
        # re-chunked to the new per-leaf padding
        for old, folded in zip(
                jax.tree_util.tree_leaves(state.grad_sync["ef"]),
                jax.tree_util.tree_leaves(new.grad_sync["ef"])):
            o = np.asarray(jax.device_get(old))
            n = np.asarray(jax.device_get(folded))
            assert o.shape[0] == 8 and n.shape[0] == 4
            for m in range(4):
                expect = o[m] + o[m + 4]
                np.testing.assert_array_equal(n[m],
                                              expect[:n.shape[1]])
                assert not expect[n.shape[1]:].any()
        # the resharded state is trainable at the new world
        cont, *_ = t4.train_epoch(new, l4.epoch(1), 1, len(l4))
        assert int(cont.step) == int(state.step) + len(l4)

    def test_fsdp_int8_state_reshards_exactly(self, mesh8, mesh4):
        """Explicit FSDP: flat-padded params AND moments re-slice, the
        per-group destination-major EF rows re-chunk leaf-by-leaf — the
        model-shaped values are preserved bit-for-bit."""
        t8, sf8, l8 = _rig(mesh8, "fsdp", "int8")
        state = sf8()
        state, *_ = t8.train_epoch(state, l8.epoch(0), 0, len(l8))
        t4, sf4, _l4 = _rig(mesh4, "fsdp", "int8")
        new = reshard_train_state(state, 8, 4, t4, sf4())

        _flat_leaves_match(state.params, new.params)
        _flat_leaves_match(state.opt_state, new.opt_state)
        # per-group EF: fold rows at the OLD stacking, then compare each
        # leaf's unpadded region through both plans' layouts
        old_plan = build_layer_plan(t4._fsdp_template, 8)
        new_plan = build_layer_plan(t4._fsdp_template, 4)
        old_groups = {g.name: g for g in old_plan.groups}
        new_groups = {g.name: g for g in new_plan.groups}
        for name, old in state.grad_sync["ef"].items():
            o = np.asarray(jax.device_get(old))
            n = np.asarray(jax.device_get(new.grad_sync["ef"][name]))
            og, ng = old_groups[name], new_groups[name]
            for m in range(4):
                folded = o[m] + o[m + 4]
                omat = folded.reshape(8, og.row_size)
                nmat = n[m].reshape(4, ng.row_size)
                ooff = noff = 0
                for co, cn in zip(og.chunk_sizes, ng.chunk_sizes):
                    oleaf = np.ascontiguousarray(
                        omat[:, ooff:ooff + co]).reshape(-1)
                    nleaf = np.ascontiguousarray(
                        nmat[:, noff:noff + cn]).reshape(-1)
                    k = min(oleaf.size, nleaf.size)
                    np.testing.assert_array_equal(nleaf[:k], oleaf[:k])
                    assert not oleaf[k:].any() and not nleaf[k:].any()
                    ooff, noff = ooff + co, noff + cn

    @pytest.mark.slow
    def test_zero1_int8_state_grows_exactly(self, mesh8, mesh4):
        """ISSUE-12: the GROW direction at state level — a zero1-int8
        state trained at world 4 reshards to the world-8 template with
        flat leaves zero-extended, EF rows zero-extended (survivors keep
        their residual bit-for-bit, newcomers start at zero), and the
        world-8 trainer trains on it.

        Slow tier (~27 s: two trainer compiles at different worlds): the
        shrink-direction twin above keeps the reshard math pinned fast,
        and the supervisor grow tests cover the grow path end to end."""
        t4, sf4, l4 = _rig(mesh4, "zero1", "int8")
        state = sf4()
        state, *_ = t4.train_epoch(state, l4.epoch(0), 0, len(l4))
        t8, sf8, l8 = _rig(mesh8, "zero1", "int8")
        new = reshard_train_state(state, 4, 8, t8, sf8())

        assert int(new.step) == int(state.step)
        _flat_leaves_match(new.params, state.params)  # grow: new >= old
        _flat_leaves_match(new.opt_state, state.opt_state)
        for old, grown in zip(
                jax.tree_util.tree_leaves(state.grad_sync["ef"]),
                jax.tree_util.tree_leaves(new.grad_sync["ef"])):
            o = np.asarray(jax.device_get(old))
            n = np.asarray(jax.device_get(grown))
            assert o.shape[0] == 4 and n.shape[0] == 8
            for m in range(4):
                np.testing.assert_array_equal(n[m][:o.shape[1]], o[m])
                assert not n[m][o.shape[1]:].any()
            assert not n[4:].any()  # returning replicas carry no error
        cont, *_ = t8.train_epoch(new, l8.epoch(1), 1, len(l8))
        assert int(cont.step) == int(state.step) + len(l8)

    @pytest.mark.slow  # ~16 s; implementation-equivalence leg — the exactness tests pin the reshard math itself
    def test_raw_reshard_matches_device_reshard(self, mesh8, mesh4,
                                                tmp_path):
        """The cross-PROCESS restore path (ISSUE 12): save a zero1-int8
        state at world 8, restore it RAW (no template — the checkpoint's
        own shapes), reshard via reshard_raw_state to world 4, and pin
        the result BITWISE against the in-process reshard_train_state of
        the live state — the fleet relaunch path and the supervisor path
        are the same re-slice."""
        from distributed_pytorch_training_tpu.resilience.elastic import (
            reshard_raw_state,
        )
        from distributed_pytorch_training_tpu.training.checkpoint import (
            CheckpointManager,
        )

        t8, sf8, l8 = _rig(mesh8, "zero1", "int8")
        state = sf8()
        state, *_ = t8.train_epoch(state, l8.epoch(0), 0, len(l8))
        mgr = CheckpointManager(str(tmp_path / "ckpt"))
        mgr.save(2, state, epoch=0, step_in_epoch=2, world_size=8)
        mgr.wait()
        raw = mgr.restore_latest_raw()
        mgr.close()
        assert raw is not None
        arrays, label, world, epoch, step = raw
        assert (label, world, epoch, step) == (2, 8, 0, 2)

        t4, sf4, _l4 = _rig(mesh4, "zero1", "int8")
        via_raw = reshard_raw_state(arrays, 8, 4, t4, sf4())
        via_live = reshard_train_state(state, 8, 4, t4, sf4())
        for a, b in zip(jax.tree_util.tree_leaves(via_raw),
                        jax.tree_util.tree_leaves(via_live)):
            np.testing.assert_array_equal(
                np.asarray(jax.device_get(a)),
                np.asarray(jax.device_get(b)))

    def test_raw_reshard_config_drift_is_loud(self, mesh8, mesh4,
                                              tmp_path):
        """A relaunch that changed the training config (here: dropped the
        int8 wire, so the EF subtree vanished from the template) must
        fail with a named leaf-count error, never a silent positional
        mis-pairing."""
        from distributed_pytorch_training_tpu.resilience.elastic import (
            reshard_raw_state,
        )
        from distributed_pytorch_training_tpu.training.checkpoint import (
            CheckpointManager,
        )

        t8, sf8, _l8 = _rig(mesh8, "zero1", "int8")
        mgr = CheckpointManager(str(tmp_path / "ckpt"))
        mgr.save(1, sf8(), epoch=0, world_size=8)
        mgr.wait()
        raw = mgr.restore_latest_raw()
        mgr.close()
        t4, sf4, _l4 = _rig(mesh4, "zero1", "fp32")
        with pytest.raises(ValueError, match="grad_sync.*training config"):
            reshard_raw_state(raw[0], 8, 4, t4, sf4())

    def test_shape_mismatch_beyond_flat_is_loud(self, mesh8, mesh4):
        """A leaf that changes shape in any way other than 1-D flat
        padding is a structure error, never a silent cast."""
        from distributed_pytorch_training_tpu.resilience.elastic import (
            _reshard_and_place,
        )

        with pytest.raises(ValueError, match="only flat-padded 1-D"):
            _reshard_and_place(
                {"x": jax.numpy.zeros((2, 3))},
                {"x": jax.numpy.zeros((3, 2))})


# ---------------------------------------------------------------------------
# checkpoint world-size manifest + template factory (satellite)
# ---------------------------------------------------------------------------


class TestCheckpointWorldSize:
    def test_manifest_records_and_probe_reads(self, mesh8, tmp_path):
        from distributed_pytorch_training_tpu.training.checkpoint import (
            CheckpointManager,
        )

        _t8, sf8, _l8 = _rig(mesh8, "zero1", "fp32")
        mgr = CheckpointManager(str(tmp_path / "ckpt"))
        mgr.save(2, sf8(), epoch=0, step_in_epoch=2, world_size=8)
        mgr.save(4, sf8(), epoch=1)  # world not recorded: legacy-style
        mgr.wait()
        assert mgr.checkpoint_world_size(2) == 8
        assert mgr.checkpoint_world_size(4) is None
        assert mgr.checkpoint_world_size(None) is None
        mgr.close()

    def test_world_mismatch_is_a_named_error(self, mesh8, mesh4,
                                             tmp_path):
        """The satellite's acceptance: a zero1 checkpoint written at world
        8 restored against a world-4 template must raise
        CheckpointWorldSizeMismatch naming BOTH sizes — not an orbax tree
        dump."""
        from distributed_pytorch_training_tpu.training.checkpoint import (
            CheckpointManager, CheckpointWorldSizeMismatch,
        )

        _t8, sf8, _l8 = _rig(mesh8, "zero1", "fp32")
        _t4, sf4, _l4 = _rig(mesh4, "zero1", "fp32")
        mgr = CheckpointManager(str(tmp_path / "ckpt"))
        mgr.save(2, sf8(), epoch=0, step_in_epoch=2, world_size=8)
        mgr.wait()
        with pytest.raises(CheckpointWorldSizeMismatch,
                           match=r"world size 8.*world size 4") as exc:
            mgr.restore_latest(sf4(), template_world_size=4)
        # the chosen candidate rides the exception so the elastic-resume
        # fallback restores it directly instead of re-scanning
        assert exc.value.label == 2 and exc.value.world_size == 8
        mgr.close()

    def test_ef_only_world_change_is_caught(self, mesh8, mesh4, tmp_path):
        """Replicated layout + int8 wire: params/opt_state shapes are
        world-independent — ONLY the (n, R) EF residual rows change with
        the world. The mismatch guard must still fire (orbax would
        silently truncate the rows otherwise); same-world restores of the
        same config stay unharassed."""
        from distributed_pytorch_training_tpu.training.checkpoint import (
            CheckpointManager, CheckpointWorldSizeMismatch,
        )

        _t8, sf8, _l8 = _rig(mesh8, "replicated", "int8")
        _t4, sf4, _l4 = _rig(mesh4, "replicated", "int8")
        mgr = CheckpointManager(str(tmp_path / "ckpt"))
        mgr.save(2, sf8(), epoch=0, step_in_epoch=2, world_size=8)
        mgr.wait()
        with pytest.raises(CheckpointWorldSizeMismatch,
                           match="EF residuals"):
            mgr.restore_latest(sf4(), template_world_size=4)
        restored = mgr.restore_latest(sf8(), template_world_size=8)
        mgr.close()
        assert restored is not None  # same world: no harassment

    def test_template_factory_probes_per_label(self, mesh8, tmp_path):
        from distributed_pytorch_training_tpu.training.checkpoint import (
            CheckpointManager,
        )

        _t8, sf8, _l8 = _rig(mesh8, "zero1", "fp32")
        mgr = CheckpointManager(str(tmp_path / "ckpt"))
        mgr.save(2, sf8(), epoch=0, step_in_epoch=2, world_size=8)
        mgr.wait()
        worlds_seen = []

        def factory(world):
            worlds_seen.append(world)
            return sf8()

        restored = mgr.restore_latest(template_factory=factory)
        mgr.close()
        assert restored is not None and worlds_seen == [8]

    def test_exactly_one_template_source(self, mesh8, tmp_path):
        from distributed_pytorch_training_tpu.training.checkpoint import (
            CheckpointManager,
        )

        mgr = CheckpointManager(str(tmp_path / "ckpt"))
        with pytest.raises(ValueError, match="exactly one"):
            mgr.restore_latest()
        mgr.close()


# ---------------------------------------------------------------------------
# the elastic-reshard analysis rule (mutation: a violating census flags)
# ---------------------------------------------------------------------------


class TestElasticReshardRule:
    def _artifact(self, expected):
        from distributed_pytorch_training_tpu.analysis.hlo_rules import (
            StepArtifacts,
        )

        text = ('%ar = f32[4096]{0} all-reduce(%x)\n'
                '%ag = f32[4096]{0} all-gather(%y)\n')
        return StepArtifacts(
            name="elastic_mut", optimized_text=text,
            config={"elastic_reshard": True,
                    "elastic_expected_census": expected},
            n_shards=4)

    def test_matching_census_passes(self):
        from distributed_pytorch_training_tpu.analysis.hlo_rules import (
            check_elastic_reshard_census,
        )

        ok = [{"op": "all-gather", "result_shape": "f32[4096]{0}",
               "count": 1},
              {"op": "all-reduce", "result_shape": "f32[4096]{0}",
               "count": 1}]
        assert check_elastic_reshard_census(self._artifact(ok)) == []

    def test_smuggled_collective_flags(self):
        """The mutation: the resharded step carries an all-gather the
        clean-at-M census does not — the rule must name it."""
        from distributed_pytorch_training_tpu.analysis.hlo_rules import (
            check_elastic_reshard_census,
        )

        clean = [{"op": "all-reduce", "result_shape": "f32[4096]{0}",
                  "count": 1}]
        findings = check_elastic_reshard_census(self._artifact(clean))
        assert findings and "all-gather" in findings[0].message

    def test_missing_expectation_flags(self):
        from distributed_pytorch_training_tpu.analysis.hlo_rules import (
            StepArtifacts, check_elastic_reshard_census,
        )

        a = StepArtifacts(name="x", optimized_text="",
                          config={"elastic_reshard": True})
        assert check_elastic_reshard_census(a)


class TestElasticGrowRule:
    """The GROW leg's census pin (ISSUE 12) — same comparator, mirror
    direction; mutation-tested like every rule."""

    def _artifact(self, expected):
        from distributed_pytorch_training_tpu.analysis.hlo_rules import (
            StepArtifacts,
        )

        text = ('%ar = f32[4096]{0} all-reduce(%x)\n'
                '%ag = f32[4096]{0} all-gather(%y)\n')
        return StepArtifacts(
            name="elastic_grow_mut", optimized_text=text,
            config={"elastic_grow": True,
                    "elastic_expected_census": expected},
            n_shards=8)

    def test_matching_census_passes(self):
        from distributed_pytorch_training_tpu.analysis.hlo_rules import (
            check_elastic_grow_census,
        )

        ok = [{"op": "all-gather", "result_shape": "f32[4096]{0}",
               "count": 1},
              {"op": "all-reduce", "result_shape": "f32[4096]{0}",
               "count": 1}]
        assert check_elastic_grow_census(self._artifact(ok)) == []

    def test_smuggled_collective_flags(self):
        """The mutation: the grown step carries an all-gather the
        clean-at-N census does not — the rule must name it."""
        from distributed_pytorch_training_tpu.analysis.hlo_rules import (
            check_elastic_grow_census,
        )

        clean = [{"op": "all-reduce", "result_shape": "f32[4096]{0}",
                  "count": 1}]
        findings = check_elastic_grow_census(self._artifact(clean))
        assert findings and "all-gather" in findings[0].message
        assert findings[0].rule == "elastic-grow-census"

    def test_inert_without_grow_config(self):
        from distributed_pytorch_training_tpu.analysis.hlo_rules import (
            StepArtifacts, check_elastic_grow_census,
        )

        a = StepArtifacts(name="x", optimized_text="",
                          config={"elastic_reshard": True})
        assert check_elastic_grow_census(a) == []

    def test_missing_expectation_flags(self):
        from distributed_pytorch_training_tpu.analysis.hlo_rules import (
            StepArtifacts, check_elastic_grow_census,
        )

        a = StepArtifacts(name="x", optimized_text="",
                          config={"elastic_grow": True})
        assert check_elastic_grow_census(a)
