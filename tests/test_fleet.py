"""resilience/fleet.py (ISSUE 12): the cross-process orchestrator.

Fast tests drive the orchestrator with STUB children (tiny scripts, no
jax): worlds planned from the capacity feed, resume decisions from the
manifest progress probe, generation/rank env stamping, exit-code
interpretation, mismatch-escape detection, launch-budget exhaustion, and
the per-generation flight accounting. The real train.py e2e — kill at
full world -> relaunch at half world -> capacity return -> relaunch at
full world, cross-world zero1 restores through train.py's elastic
--resume, final checkpoint bitwise vs an uninterrupted control child —
is the slow test at the bottom (also: `resilience fleet`).
"""

import json
import sys
from pathlib import Path

import pytest

from distributed_pytorch_training_tpu.resilience.fleet import (
    DIST_COORD_ENV, DIST_NPROC_ENV, DIST_PROC_ID_ENV,
    FLEET_GENERATION_ENV, FLEET_RANK_ENV, FleetOrchestrator,
    _xla_flags_for, check_fleet_flights, checkpoint_progress,
)

REPO = Path(__file__).resolve().parent.parent

# One scripted child: reads its generation from the env, records what it
# saw (argv tail + env) into the checkpoint dir, optionally fakes
# checkpoint progress by writing a manifest, optionally prints a line,
# and exits with the scripted rc.
STUB = """\
import json, os, sys
from pathlib import Path

gen = int(os.environ["{gen_env}"])
ckpt = Path(sys.argv[1])
plans = json.loads(Path(sys.argv[2]).read_text())
plan = plans[min(gen, len(plans) - 1)]
ckpt.mkdir(parents=True, exist_ok=True)
(ckpt / "seen_gen{{}}.json".format(gen)).write_text(json.dumps({{
    "args": sys.argv[3:],
    "rank": os.environ.get("{rank_env}"),
    "xla": os.environ.get("XLA_FLAGS", ""),
    "platform": os.environ.get("JAX_PLATFORMS", ""),
}}))
if plan.get("step") is not None:
    mdir = ckpt / ".manifests"
    mdir.mkdir(exist_ok=True)
    (mdir / "{{}}.json".format(plan["label"])).write_text(json.dumps(
        {{"step": plan["step"], "world_size": plan.get("world")}}))
if plan.get("print"):
    print(plan["print"])
sys.exit(plan["rc"])
""".format(gen_env=FLEET_GENERATION_ENV, rank_env=FLEET_RANK_ENV)


def _orchestrator(tmp_path, plans, capacity, *, global_batch=16,
                  target_step=12, max_launches=8, on_child_exit=None):
    stub = tmp_path / "stub_child.py"
    stub.write_text(STUB)
    plan_file = tmp_path / "plans.json"
    plan_file.write_text(json.dumps(plans))
    ckpt = tmp_path / "ckpt"

    def argv_for(world, generation, resume):
        return [sys.executable, str(stub), str(ckpt), str(plan_file),
                f"world={world}", f"resume={resume}"]

    return FleetOrchestrator(
        argv_for, ckpt, global_batch=global_batch,
        target_step=target_step, capacity_for=capacity,
        max_launches=max_launches, on_child_exit=on_child_exit,
        log=lambda _m: None), ckpt


def _seen(ckpt, generation):
    return json.loads((ckpt / f"seen_gen{generation}.json").read_text())


class TestCheckpointProgress:
    def test_empty_and_missing_dir(self, tmp_path):
        assert checkpoint_progress(tmp_path) == (-1, None)
        assert checkpoint_progress(tmp_path / "nope") == (-1, None)

    def test_newest_finalized_label_wins(self, tmp_path):
        mdir = tmp_path / ".manifests"
        mdir.mkdir()
        (mdir / "4.json").write_text(json.dumps({"step": 4,
                                                 "world_size": 8}))
        (mdir / "10.json").write_text(json.dumps({"step": 10,
                                                  "world_size": 4}))
        assert checkpoint_progress(tmp_path) == (10, 4)

    def test_torn_and_foreign_manifests_ignored(self, tmp_path):
        mdir = tmp_path / ".manifests"
        mdir.mkdir()
        (mdir / "4.json").write_text(json.dumps({"step": 4}))
        (mdir / "12.json").write_text("{ torn")       # unparseable
        (mdir / "notes.json").write_text("{}")        # non-integer stem
        assert checkpoint_progress(tmp_path) == (4, None)


class TestXlaFlags:
    def test_replaces_inherited_device_count(self):
        out = _xla_flags_for(
            4, "--xla_cpu_foo=1 --xla_force_host_platform_device_count=8")
        assert out == ("--xla_cpu_foo=1 "
                       "--xla_force_host_platform_device_count=4")
        assert _xla_flags_for(2) == \
            "--xla_force_host_platform_device_count=2"


class TestOrchestrator:
    def test_kill_shrink_grow_scenario(self, tmp_path):
        """The canonical sequence with stub children: gen0 crashes at
        world 8 having checkpointed step 4; gen1 (capacity 4 -> world 4,
        --resume) drains at step 10; gen2 (capacity back to 8) completes
        at step 12. Worlds follow plan_elastic_world(capacity), resume
        follows the manifest probe, every child is stamped with its
        generation/rank and a world-sized device count."""
        events = []
        plans = [
            {"rc": 1, "label": 4, "step": 4, "world": 8},
            {"rc": 0, "label": 10, "step": 10, "world": 4},
            {"rc": 0, "label": 12, "step": 12, "world": 8},
        ]
        orch, ckpt = _orchestrator(
            tmp_path, plans, [8, 4, 8],
            on_child_exit=lambda gen, launch: events.append(
                (gen, launch.outcome)))
        report = orch.run()
        assert report.completed is True
        assert report.relaunches == 2
        assert [l["world"] for l in report.launches] == [8, 4, 8]
        assert [l["outcome"] for l in report.launches] == \
            ["crashed", "drained", "completed"]
        assert [l["resume"] for l in report.launches] == \
            [False, True, True]
        assert report.final_step == 12 and report.final_world == 8
        assert report.mismatch_escapes == 0 and report.errors == []
        assert events == [(0, "crashed"), (1, "drained"),
                          (2, "completed")]
        for gen, world in ((0, 8), (1, 4), (2, 8)):
            seen = _seen(ckpt, gen)
            assert seen["rank"] == "0"
            assert seen["platform"] == "cpu"
            assert (f"--xla_force_host_platform_device_count={world}"
                    in seen["xla"])
            assert seen["args"] == [f"world={world}",
                                    f"resume={gen > 0}"]

    def test_capacity_feed_callable_and_non_divisor(self, tmp_path):
        """A callable capacity feed, and a non-divisor capacity (7 of
        global batch 16) planning down to the largest feasible world."""
        plans = [{"rc": 0, "label": 12, "step": 12, "world": 4}]
        orch, _ckpt = _orchestrator(tmp_path, plans, lambda gen: 7)
        report = orch.run()
        assert report.completed
        assert [l["world"] for l in report.launches] == [4]
        assert report.launches[0]["available"] == 7

    def test_relay_death_rc70_is_named_and_relaunched(self, tmp_path):
        plans = [
            {"rc": 70, "label": 4, "step": 4, "world": 8},
            {"rc": 0, "label": 12, "step": 12, "world": 8},
        ]
        orch, _ckpt = _orchestrator(tmp_path, plans, [8])
        report = orch.run()
        assert report.completed
        assert [l["outcome"] for l in report.launches] == \
            ["relay_death", "completed"]

    def test_mismatch_escape_is_counted(self, tmp_path):
        """A CheckpointWorldSizeMismatch surfacing in a child's output is
        the exact failure the orchestrator exists to absorb — counted as
        a hard error (the acceptance gate: zero escapes)."""
        plans = [
            {"rc": 1, "print": "CheckpointWorldSizeMismatch: checkpoint "
                               "was written at world size 8"},
            {"rc": 0, "label": 12, "step": 12, "world": 8},
        ]
        orch, _ckpt = _orchestrator(tmp_path, plans, [8])
        report = orch.run()
        assert report.completed  # the fleet still recovered...
        assert report.mismatch_escapes == 1  # ...but the gate must fail
        assert any("CheckpointWorldSizeMismatch" in e
                   for e in report.errors)

    def test_launch_budget_exhaustion(self, tmp_path):
        plans = [{"rc": 0}]  # exits clean, never makes progress
        orch, _ckpt = _orchestrator(tmp_path, plans, [8], max_launches=3)
        report = orch.run()
        assert not report.completed
        assert len(report.launches) == 3
        assert all(l["outcome"] == "drained" for l in report.launches)
        assert any("did not reach step" in e for e in report.errors)


# Multi-host stub child (ISSUE 20): every rank records the rendezvous
# contract it was stamped with; only rank 0 writes checkpoint progress
# (as in a real run, where rank 0 owns the manifest). Per-rank exit
# codes come from the plan's "rcs" list.
MH_STUB = """\
import json, os, sys
from pathlib import Path

gen = int(os.environ["{gen_env}"])
rank = int(os.environ.get("{proc_env}", "0"))
ckpt = Path(sys.argv[1])
plans = json.loads(Path(sys.argv[2]).read_text())
plan = plans[min(gen, len(plans) - 1)]
ckpt.mkdir(parents=True, exist_ok=True)
(ckpt / "mh_gen{{}}_rank{{}}.json".format(gen, rank)).write_text(
    json.dumps({{
        "args": sys.argv[3:],
        "coord": os.environ.get("{coord_env}"),
        "nproc": os.environ.get("{nproc_env}"),
        "proc_id": os.environ.get("{proc_env}"),
        "fleet_rank": os.environ.get("{rank_env}"),
        "xla": os.environ.get("XLA_FLAGS", ""),
    }}))
if rank == 0 and plan.get("step") is not None:
    mdir = ckpt / ".manifests"
    mdir.mkdir(exist_ok=True)
    (mdir / "{{}}.json".format(plan["label"])).write_text(json.dumps(
        {{"step": plan["step"], "world_size": plan.get("world")}}))
rcs = plan.get("rcs") or [plan.get("rc", 0)]
sys.exit(rcs[min(rank, len(rcs) - 1)])
""".format(gen_env=FLEET_GENERATION_ENV, rank_env=FLEET_RANK_ENV,
           coord_env=DIST_COORD_ENV, nproc_env=DIST_NPROC_ENV,
           proc_env=DIST_PROC_ID_ENV)


class TestMultiHostGenerations:
    """hosts > 1 (ISSUE 20): one generation spans `hosts` processes
    rendezvousing through the stamped DPT_COORDINATOR_ADDRESS /
    DPT_NUM_PROCESSES / DPT_PROCESS_ID contract."""

    PORT = 7310

    def _mh_orchestrator(self, tmp_path, plans, capacity, *, hosts=2,
                         target_step=12, max_launches=8):
        stub = tmp_path / "mh_stub_child.py"
        stub.write_text(MH_STUB)
        plan_file = tmp_path / "plans.json"
        plan_file.write_text(json.dumps(plans))
        ckpt = tmp_path / "ckpt"

        def argv_for(world, generation, resume, rank):
            # multi-host argv_for receives the child's rank explicitly
            return [sys.executable, str(stub), str(ckpt), str(plan_file),
                    f"world={world}", f"resume={resume}", f"rank={rank}"]

        return FleetOrchestrator(
            argv_for, ckpt, global_batch=16, target_step=target_step,
            capacity_for=capacity, max_launches=max_launches,
            hosts=hosts, coordinator_port=self.PORT,
            log=lambda _m: None), ckpt

    @staticmethod
    def _mh_seen(ckpt, generation, rank):
        return json.loads(
            (ckpt / f"mh_gen{generation}_rank{rank}.json").read_text())

    def test_requires_coordinator_port(self, tmp_path):
        with pytest.raises(ValueError, match="coordinator_port"):
            FleetOrchestrator(
                lambda **_kw: [sys.executable, "-c", "pass"],
                tmp_path / "ckpt", global_batch=16, target_step=12,
                capacity_for=[8], hosts=2)

    def test_topology_stamped_and_peers_collected(self, tmp_path):
        """Every rank of a 2-host generation sees the same coordinator
        address, nproc=2, its own process id, and world//hosts local
        devices; rank 1's rc is collected into peer_rcs and its output
        lands in a per-rank log."""
        plans = [{"rc": 0, "label": 12, "step": 12, "world": 8}]
        orch, ckpt = self._mh_orchestrator(tmp_path, plans, [8])
        report = orch.run()
        assert report.completed is True
        assert len(report.launches) == 1
        assert report.launches[0]["peer_rcs"] == [0]
        for rank in (0, 1):
            seen = self._mh_seen(ckpt, 0, rank)
            assert seen["coord"] == f"127.0.0.1:{self.PORT}"
            assert seen["nproc"] == "2"
            assert seen["proc_id"] == str(rank)
            # one generation at world 8 over 2 hosts: 4 local devices
            assert ("--xla_force_host_platform_device_count=4"
                    in seen["xla"])
            assert f"rank={rank}" in seen["args"]
        # FLEET_RANK stays the single-host restart-lineage rank (0 for
        # every child of the generation); the collective rank is
        # DPT_PROCESS_ID
        assert self._mh_seen(ckpt, 0, 1)["fleet_rank"] == "1"
        assert (ckpt / "fleet_logs" / "gen0_rank1.log").exists()

    def test_peer_crash_downgrades_and_port_advances(self, tmp_path):
        """Rank 0 exiting clean does not absolve a dead peer: the
        generation is crashed and relaunched — and the relaunch
        rendezvouses on coordinator_port + generation, never racing the
        previous coordinator's socket."""
        plans = [
            {"rcs": [0, 1], "label": 4, "step": 4, "world": 8},
            {"rcs": [0, 0], "label": 12, "step": 12, "world": 8},
        ]
        orch, ckpt = self._mh_orchestrator(tmp_path, plans, [8])
        report = orch.run()
        assert report.completed is True
        assert [l["outcome"] for l in report.launches] == \
            ["crashed", "completed"]
        assert [l["peer_rcs"] for l in report.launches] == [[1], [0]]
        assert [l["resume"] for l in report.launches] == [False, True]
        for gen in (0, 1):
            for rank in (0, 1):
                assert self._mh_seen(ckpt, gen, rank)["coord"] == \
                    f"127.0.0.1:{self.PORT + gen}"


class TestFleetFlights:
    def _flight(self, d, name, cause, gen):
        (d / name).write_text(json.dumps(
            {"cause": cause, "fleet_generation": gen}))

    def test_one_flight_per_abnormal_exit(self, tmp_path):
        self._flight(tmp_path, "flight_1_0.json",
                     "FaultError: injected crash@step=6 "
                     "[fleet gen=0 rank=0]", "0")
        self._flight(tmp_path, "flight_2_0.json",
                     "preemption (sigterm) drained at epoch 2 step 2 "
                     "[fleet gen=1 rank=0]", "1")
        launches = [
            {"generation": 0, "outcome": "crashed"},
            {"generation": 1, "outcome": "drained"},
            {"generation": 2, "outcome": "completed"},
        ]
        stats = check_fleet_flights(tmp_path, launches)
        assert stats["flights_ok"] is True
        assert stats["flight_problems"] == []

    def test_missing_and_surplus_flights_flag(self, tmp_path):
        self._flight(tmp_path, "flight_3_0.json",
                     "stray [fleet gen=2 rank=0]", "2")
        launches = [
            {"generation": 0, "outcome": "crashed"},   # no flight: bad
            {"generation": 2, "outcome": "completed"},  # flight: bad
        ]
        stats = check_fleet_flights(tmp_path, launches)
        assert stats["flights_ok"] is False
        assert len(stats["flight_problems"]) == 2

    def test_pre_existing_flights_are_ignored(self, tmp_path):
        """A reused --ckpt-dir's stale postmortems (a previous fleet run)
        must neither satisfy nor fail THIS run's accounting — the same
        guard the chaos harness applies."""
        stale = tmp_path / "flight_0_0.json"
        self._flight(tmp_path, "flight_0_0.json",
                     "old crash [fleet gen=0 rank=0]", "0")
        launches = [{"generation": 0, "outcome": "completed"}]
        # without the exclusion the completed gen-0 'left' a flight: bad
        assert check_fleet_flights(tmp_path, launches)["flights_ok"] \
            is False
        stats = check_fleet_flights(tmp_path, launches, ignore={stale})
        assert stats["flights_ok"] is True and stats["flights"] == []

    def test_drained_flight_must_name_preemption(self, tmp_path):
        self._flight(tmp_path, "flight_4_0.json",
                     "something else [fleet gen=0 rank=0]", "0")
        stats = check_fleet_flights(
            tmp_path, [{"generation": 0, "outcome": "drained"}])
        assert stats["flights_ok"] is False
        assert "not a preemption" in stats["flight_problems"][0]


class TestWatchAndScrapeWiring:
    """ISSUE 14: the orchestrator's live watch — child cleanup on an
    interrupted watch, and the metrics-port stamping contract."""

    def test_exception_in_watch_kills_the_child(self, tmp_path,
                                                monkeypatch):
        """subprocess.run's kill-on-exception contract, kept across the
        Popen switch: a Ctrl-C (or raising callback) mid-watch must not
        orphan a running training child."""
        import subprocess as sp

        sleeper = tmp_path / "sleeper.py"
        sleeper.write_text("import time\ntime.sleep(600)\n")
        ckpt = tmp_path / "ckpt"
        orch = FleetOrchestrator(
            lambda world, generation, resume: [sys.executable,
                                               str(sleeper)],
            ckpt, global_batch=16, target_step=12, capacity_for=[8],
            max_launches=1, log=lambda _m: None)
        started: list = []
        real_popen = sp.Popen

        def capture_popen(*args, **kwargs):
            proc = real_popen(*args, **kwargs)
            started.append(proc)
            return proc

        monkeypatch.setattr(sp, "Popen", capture_popen)

        def boom(proc, launch, generation):
            raise KeyboardInterrupt

        monkeypatch.setattr(orch, "_watch_child", boom)
        with pytest.raises(KeyboardInterrupt):
            orch.run()
        (proc,) = started
        assert proc.poll() is not None   # killed, not orphaned

    def test_metrics_port_stamp_is_the_base_port(self, tmp_path):
        """The child applies its own rank offset (resolve_metrics_port
        reads DPT_FLEET_RANK), so the orchestrator stamps the BASE port
        — base+rank here would offset twice."""
        from distributed_pytorch_training_tpu.telemetry.metrics_http import (
            METRICS_PORT_ENV, resolve_metrics_port,
        )

        orch, _ = _orchestrator(tmp_path, [{"rc": 0}], [8])
        orch.metrics_port = 9200
        env0 = orch._child_env(8, 0, rank=0)
        env2 = orch._child_env(8, 0, rank=2)
        assert env0[METRICS_PORT_ENV] == "9200"
        assert env2[METRICS_PORT_ENV] == "9200"
        # ... and the child-side resolution lands each rank on its own
        # port from that one stamped value
        assert resolve_metrics_port(None, rank=0) == 0  # env unset here
        import os
        os.environ[METRICS_PORT_ENV] = env2[METRICS_PORT_ENV]
        try:
            assert resolve_metrics_port(None, rank=2) == 9202
        finally:
            del os.environ[METRICS_PORT_ENV]

    def test_no_metrics_port_leaves_env_unstamped(self, tmp_path):
        from distributed_pytorch_training_tpu.telemetry.metrics_http import (
            METRICS_PORT_ENV,
        )

        orch, _ = _orchestrator(tmp_path, [{"rc": 0}], [8])
        assert METRICS_PORT_ENV not in orch._child_env(8, 0)


def test_federation_port_requires_metrics_port():
    """The fan-in proxies the children's per-rank metrics ports — asking
    for it without any child port is a misconfiguration named upfront,
    not a late 'merged page is empty' verdict failure."""
    import pytest

    from distributed_pytorch_training_tpu.resilience.__main__ import main

    with pytest.raises(SystemExit, match="requires --metrics-port"):
        main(["fleet", "--federation-port", "19000"])


def test_fleet_command_registered():
    """`resilience fleet` parses (the console-script surface) and the
    orchestrator module is importable without jax initialized."""
    import distributed_pytorch_training_tpu.resilience.fleet as fleet_mod

    assert callable(fleet_mod.fleet_main)
    from distributed_pytorch_training_tpu.resilience.__main__ import main

    # unknown option after the command must be a usage error, proving the
    # subcommand is wired into the entry point's parser
    with pytest.raises(SystemExit):
        main(["fleet", "--no-such-option"])


@pytest.mark.slow
def test_fleet_cli_e2e_kill_shrink_grow_bitwise(tmp_path, capsys,
                                                monkeypatch):
    """ISSUE-12 acceptance: the real train.py fleet — a zero1 child
    killed at full world, relaunched at half world (cross-world restore
    through train.py's elastic --resume: raw restore + reshard, flat
    moments re-sliced), drained by SIGTERM, relaunched at full world on
    capacity return, completing with the final checkpoint BITWISE equal
    to an uninterrupted control child continuing from the last handoff.
    One attributable flight per abnormal child exit; zero
    CheckpointWorldSizeMismatch escapes.

    Extended for ISSUE 14: the default schedule also injects a
    loader_stall into generation 2, and the run must yield ONE merged
    fleet summary + ONE stitched Perfetto trace covering every
    generation (exactly one pid per (gen, rank)), with the stall rank-
    AND phase-attributed in the straggler table; every child serves
    /metrics (port stamped by the orchestrator) and at least one live
    scrape must have answered with the step counter.

    Extended for ISSUE 15: ONE federated /metrics page (the fan-in
    proxy over the children's ports) must end the run carrying
    gen/rank-labelled step rows for every scraped generation, and the
    gen-2 loader_stall — with the children's watchdog warm-up shortened
    via the env knobs — must auto-arm a capture whose device_profile
    upgrades the straggler verdict to device-attributed."""
    from distributed_pytorch_training_tpu.resilience.__main__ import main

    # watchdog tuning for the children (env-inherited): the gen-2 stall
    # lands on the FIRST post-resume step, where the rolling median has
    # no warm-up — the absolute stall bound is the detector for exactly
    # that; the spike bar stays high so CPU noise cannot arm competing
    # captures
    monkeypatch.setenv("DPT_WATCHDOG_STALL_ABS_S", "1.0")
    monkeypatch.setenv("DPT_WATCHDOG_SPIKE_FACTOR", "1000.0")
    rc = main(["fleet", "--layout", "zero1",
               "--ckpt-dir", str(tmp_path), "--metrics-port", "19377",
               "--federation-port", "19397",
               "--json"])
    stats = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    assert stats["completed"] is True
    assert stats["parity_bitwise"] is True
    assert stats["mismatch_escapes"] == 0
    assert stats["worlds"] == [8, 4, 8]
    assert [l["outcome"] for l in stats["launches"]] == \
        ["crashed", "drained", "completed"]
    assert stats["flights_ok"] is True
    causes = [f["cause"] or "" for f in stats["flights"]]
    assert any("crash@step" in c and "[fleet gen=0" in c for c in causes)
    assert any("preemption" in c and "[fleet gen=1" in c for c in causes)
    # both cross-world restores rode the elastic resume path
    logs = sorted((Path(stats["dir"]) / "ckpt" /
                   "fleet_logs").glob("gen*.log"))
    resumed = [p.read_text(errors="replace") for p in logs[1:]]
    assert all("ELASTIC RESUME" in t for t in resumed)

    # --- the merged fleet view (ISSUE 14 acceptance) ---
    summary = stats["fleet_summary"]
    assert summary is not None and summary["n_streams"] == 3
    assert summary["identities"] == [[0, 0], [1, 0], [2, 0]]  # json lists
    assert Path(stats["fleet_summary_path"]).is_file()
    # the injected loader_stall on gen 2 is rank- AND phase-attributed
    assert stats["straggler_attributed"] is True
    hits = [s for s in stats["stragglers"]
            if s["gen"] == 2 and s["phase"] == "data_wait"]
    assert hits and hits[0]["dur_s"] >= 1.0
    # ONE stitched trace, exactly one pid/tid pair per (gen, rank)
    trace = json.loads(Path(stats["fleet_trace_path"]).read_text())
    names = {e["args"]["name"]: e["pid"]
             for e in trace["traceEvents"] if e["ph"] == "M"}
    assert names == {"gen0/rank0": 1, "gen1/rank0": 2, "gen2/rank0": 3}
    span_keys = {(e["pid"], e["tid"])
                 for e in trace["traceEvents"] if e["ph"] == "X"}
    assert {pid for pid, _ in span_keys} == {1, 2, 3}
    # host spans on tid 1; device_profile windows (ISSUE 15) on tid 2
    assert all(tid in (1, 2) for _, tid in span_keys)
    assert all(e.get("name") == "device_profile"
               for e in trace["traceEvents"]
               if e["ph"] == "X" and e["tid"] == 2)
    # the live /metrics smoke answered during at least one child
    assert stats["metrics_smoke"] is True
    assert any(l["metrics_ok"] for l in stats["launches"])
    # and the tail thread saw live per-generation progress
    assert any(l["live_last_step"] >= 0 for l in stats["launches"])

    # --- the device-time attribution plane (ISSUE 15 acceptance) ---
    # the injected stall auto-armed a capture in the gen-2 child and the
    # straggler verdict carries the device block (span attribution above
    # remains the gate; this is the upgrade)
    assert stats["straggler_device_attributed"] is True
    dev_hits = [s for s in stats["stragglers"] if s.get("device")]
    assert dev_hits and dev_hits[0]["device"]["reason"] \
        == "anomaly:loader_stall"
    # ONE federated page, gen/rank-labelled rows for every generation
    # that provably served /metrics while alive
    assert stats["federation_ok"] is True
    page = Path(stats["federation_page_path"]).read_text()
    scraped = {str(l["generation"]) for l in stats["launches"]
               if l.get("metrics_ok")}
    for gen in scraped:
        assert f'dpt_steps_total{{gen="{gen}",rank="0"}}' in page
    assert "dpt_federation_up{" in page and "dpt_build_info{" in page
