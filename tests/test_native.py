"""Native C++ data-runtime parity tests (native/src/dpt_native.cpp).

Every native entry point must agree byte-for-byte with its NumPy fallback —
the same role the reference delegates to DataLoader workers + torchvision C++
ops (/root/reference/train_ddp.py:131-148; SURVEY.md §2b).
"""

import numpy as np
import pytest

from distributed_pytorch_training_tpu import native
from distributed_pytorch_training_tpu.data import ShardedLoader
from distributed_pytorch_training_tpu.data.datasets import (
    synthetic_image_dataset,
)

pytestmark = pytest.mark.skipif(
    not native.is_available(), reason="native toolchain unavailable")


def test_chw_to_hwc_matches_numpy():
    rec = np.random.RandomState(0).randint(0, 256, (33, 3 * 32 * 32)).astype(np.uint8)
    got = native.chw_to_hwc_u8(rec, 3, 32, 32)
    want = rec.reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    assert np.array_equal(got, want)


def test_gather_rows_matches_fancy_index():
    src = np.random.RandomState(1).randint(0, 256, (200, 8, 8, 3)).astype(np.uint8)
    idx = np.random.RandomState(2).randint(0, 200, 77)
    assert np.array_equal(native.gather_rows(src, idx), src[idx])


def test_gather_rows_non_uint8_dtypes():
    """The gather is byte-wise: int32 token rows and float32 rows round-trip
    exactly (TokenLoader depends on this)."""
    for dtype in (np.int32, np.float32, np.uint16):
        src = (np.random.RandomState(3).rand(50, 12) * 100).astype(dtype)
        idx = np.random.RandomState(4).randint(0, 50, 31)
        assert np.array_equal(native.gather_rows(src, idx), src[idx]), dtype


def test_permutation_is_deterministic_permutation():
    p = native.permutation(42, 5000)
    assert np.array_equal(np.sort(p), np.arange(5000))
    assert np.array_equal(p, native.permutation(42, 5000))
    assert not np.array_equal(p, native.permutation(43, 5000))


def test_permutation_python_fallback_bit_identical():
    """Toolchain-less hosts must shuffle identically to native hosts (multi-
    host shard consistency): the Python mirror follows the same splitmix64
    Fisher-Yates stream."""
    for seed, n in ((42, 1), (42, 257), (7, 4096)):
        assert np.array_equal(native.permutation(seed, n),
                              native._permutation_py(seed, n))


def test_prefetcher_yields_exact_batches_in_order():
    images = np.random.RandomState(3).randint(0, 256, (100, 4, 4, 3)).astype(np.uint8)
    labels = np.random.RandomState(4).randint(0, 10, 100).astype(np.int32)
    steps, batch = 9, 16
    idx = np.random.RandomState(5).randint(0, 100, (steps, batch)).astype(np.int64)
    w = np.random.RandomState(6).rand(steps, batch).astype(np.float32)
    pf = native.NativePrefetcher(images, labels, idx, w, depth=2)
    for t, (img, lab, weight) in enumerate(pf):
        assert np.array_equal(img, images[idx[t]])
        assert np.array_equal(lab, labels[idx[t]])
        assert np.allclose(weight, w[t])
    assert t == steps - 1


def test_prefetcher_early_close_does_not_hang():
    images = np.zeros((50, 4, 4, 3), np.uint8)
    labels = np.zeros(50, np.int32)
    idx = np.zeros((20, 8), np.int64)
    w = np.ones((20, 8), np.float32)
    pf = native.NativePrefetcher(images, labels, idx, w, depth=2)
    assert pf.next() is not None
    pf.close()
    assert pf.next() is None


def test_loader_native_path_matches_python_path(mesh8):
    """ShardedLoader output is identical whether batches come from the C++
    prefetcher or the Python fallback (same sampler plan, same arrays)."""
    ds = synthetic_image_dataset(70, (8, 8), 4, seed=0)
    loader = ShardedLoader(ds, mesh8, per_device_batch=4, shuffle=True, seed=7)

    native_batches = [
        {k: np.asarray(v) for k, v in b.items()}
        for b in loader._native_epoch(epoch=1)
    ]
    python_batches = [
        {k: np.asarray(v) for k, v in b.items()}
        for b in loader._python_epoch(epoch=1)
    ]
    assert len(native_batches) == len(python_batches) == len(loader)
    for nb, pb in zip(native_batches, python_batches):
        for k in ("image", "label", "weight"):
            assert np.array_equal(nb[k], pb[k]), k
