"""Attention kernel tests: flash (Pallas, interpreter mode on CPU) and ring
(shard_map over the seq axis) against the XLA reference — values and
gradients (SURVEY.md §5 long-context requirements)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_training_tpu.models.layers import dot_product_attention
from distributed_pytorch_training_tpu.ops import (
    flash_attention,
    make_flash_attention_fn,
    make_ring_attention_fn,
    ring_attention,
)
from distributed_pytorch_training_tpu.parallel import MeshSpec, build_mesh


def _rand_qkv(b=2, s=128, h=4, d=32, seed=0):
    rng = np.random.RandomState(seed)
    shape = (b, s, h, d)
    q = rng.randn(*shape).astype(np.float32) * 0.5
    k = rng.randn(*shape).astype(np.float32) * 0.5
    v = rng.randn(*shape).astype(np.float32) * 0.5
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


def _ref(q, k, v, causal):
    mask = None
    if causal:
        mask = jnp.tril(jnp.ones((q.shape[1], k.shape[1]), bool))[None, None]
    return dot_product_attention(q, k, v, mask=mask)


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, causal):
        q, k, v = _rand_qkv()
        out = flash_attention(q, k, v, causal, None, 64, 64)
        expect = _ref(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   rtol=2e-5, atol=2e-5)

    def test_uneven_blocks_auto_fit(self):
        # 100 has no divisor that is a multiple of 8, so the block picker
        # falls back to spanning the axis — still correct, never an error.
        q, k, v = _rand_qkv(s=100)
        out = flash_attention(q, k, v, False, None, 64, 64)
        expect = _ref(q, k, v, False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   rtol=2e-5, atol=2e-5)
        # 96 = 12 blocks of 8: picker takes the largest <=64 divisor (48).
        from distributed_pytorch_training_tpu.ops.flash_attention import (
            _fit_block,
        )
        assert _fit_block(64, 96) == 48
        assert _fit_block(64, 100) == 100
        assert _fit_block(512, 1024) == 512
        assert _fit_block(512, 384) == 384
        # degenerate divisors (8 | 2056 but grid would be 257 tiny tiles)
        # and sub-8 requests must not produce pathological kernels
        with pytest.raises(ValueError, match="block"):
            _fit_block(512, 2056)
        assert _fit_block(4, 2048) == 8
        assert _fit_block(512, 1032) == 344  # >= s//8 floor keeps the grid sane

    def test_gradients_match_reference(self):
        q, k, v = _rand_qkv(b=1, s=64, h=2, d=16)

        def loss_flash(q, k, v):
            return (flash_attention(q, k, v, True, None, 32, 32) ** 2).sum()

        def loss_ref(q, k, v):
            return (_ref(q, k, v, True) ** 2).sum()

        g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_flash, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4)


    def test_long_context_grad_parity_s4096(self):
        """S=4096 forward+backward through the blockwise Pallas kernels
        (interpreter mode) vs the XLA reference — the long-context bar from
        SURVEY.md §5. The r2 backward was an O(S^2) recompute; this exercises
        the real dq/dk/dv kernels at a length where the (S,S) score matrix
        (64 MB fp32 per head) would no longer be a reasonable residual."""
        q, k, v = _rand_qkv(b=1, s=4096, h=1, d=64, seed=3)

        def loss_flash(q, k, v):
            return (flash_attention(q, k, v, True, None, 512, 512) ** 2).sum()

        def loss_ref(q, k, v):
            return (_ref(q, k, v, True) ** 2).sum()

        g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g_flash, g_ref, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-3,
                err_msg=f"d{name} diverges at S=4096")

    def test_bf16_grad_parity(self):
        """bf16 inputs (the TPU compute dtype): kernel stats stay fp32, so
        grads must track the fp32-stat reference within bf16 tolerance."""
        q, k, v = _rand_qkv(b=1, s=256, h=2, d=32, seed=4)
        qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))

        def loss_flash(q, k, v):
            return (flash_attention(q, k, v, True, None, 128, 128)
                    .astype(jnp.float32) ** 2).sum()

        def loss_ref(q, k, v):
            return (_ref(q.astype(jnp.float32), k.astype(jnp.float32),
                         v.astype(jnp.float32), True) ** 2).sum()

        g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(qb, kb, vb)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(qb, kb, vb)
        for a, b in zip(g_flash, g_ref):
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                rtol=0.1, atol=0.5)

    def test_adapter_rejects_mask(self):
        fn = make_flash_attention_fn(causal=True)
        q, k, v = _rand_qkv(s=64)
        with pytest.raises(ValueError, match="mask"):
            fn(q, k, v, mask=jnp.ones((1, 1, 64, 64), bool))


class TestRingAttention:
    @pytest.fixture(scope="class")
    def seq_mesh(self, devices):
        return build_mesh(MeshSpec(data=2, seq=4), devices=devices)

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, seq_mesh, causal):
        q, k, v = _rand_qkv(b=2, s=64, h=2, d=16)
        out = jax.jit(lambda q, k, v: ring_attention(
            q, k, v, seq_mesh, causal=causal))(q, k, v)
        expect = _ref(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   rtol=2e-5, atol=2e-5)

    def test_gradients_flow_through_ring(self, seq_mesh):
        q, k, v = _rand_qkv(b=2, s=32, h=2, d=8)

        def loss_ring(q, k, v):
            return (ring_attention(q, k, v, seq_mesh, causal=True) ** 2).sum()

        def loss_ref(q, k, v):
            return (_ref(q, k, v, True) ** 2).sum()

        g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ring, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4)

    def test_seq_axis_1_degrades_gracefully(self, devices):
        # mesh with seq=1: ring of length 1 == plain attention
        mesh = build_mesh(MeshSpec(data=2), devices=devices[:2])
        q, k, v = _rand_qkv(b=2, s=32, h=2, d=8)
        out = jax.jit(lambda q, k, v: ring_attention(
            q, k, v, mesh, causal=True))(q, k, v)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(_ref(q, k, v, True)),
                                   rtol=2e-5, atol=2e-5)


class TestModelKernelIntegration:
    def test_gpt2_flash_matches_xla(self):
        from distributed_pytorch_training_tpu.models import get_model

        ids = jnp.asarray(np.random.RandomState(0).randint(0, 1000, (2, 64)))
        m_xla = get_model("gpt2_124m", max_position=64)
        variables = m_xla.init(jax.random.PRNGKey(0), ids, train=False)
        out_xla = m_xla.apply(variables, ids, train=False)

        m_flash = get_model("gpt2_124m", max_position=64,
                            attention_fn=make_flash_attention_fn(
                                causal=True, block_q=32, block_k=32))
        out_flash = m_flash.apply(variables, ids, train=False)
        np.testing.assert_allclose(np.asarray(out_xla), np.asarray(out_flash),
                                   rtol=3e-4, atol=3e-4)

    def test_gpt2_kernel_path_rejects_padding_mask(self):
        from distributed_pytorch_training_tpu.models import get_model

        ids = jnp.zeros((1, 32), jnp.int32)
        m = get_model("gpt2_124m", max_position=32,
                      attention_fn=make_flash_attention_fn(causal=True,
                                                           block_q=32,
                                                           block_k=32))
        variables = m.init(jax.random.PRNGKey(0), ids, train=False)
        with pytest.raises(ValueError, match="padding masks"):
            m.apply(variables, ids, attention_mask=jnp.ones((1, 32)),
                    train=False)


class TestRingAttentionChunked:
    """The q-chunked ring body (bounded per-step score memory) must be a
    pure memory trade: same values, same grads as the straight-through
    block — exercised by forcing q_chunk below the shard length."""

    @pytest.fixture(scope="class")
    def seq_mesh(self, devices):
        return build_mesh(MeshSpec(data=2, seq=4), devices=devices)

    @pytest.mark.parametrize("causal", [False, True])
    def test_chunked_matches_reference(self, seq_mesh, causal):
        q, k, v = _rand_qkv(b=2, s=128, h=2, d=16)  # S_loc=32, chunks of 8
        out = jax.jit(lambda q, k, v: ring_attention(
            q, k, v, seq_mesh, causal=causal, q_chunk=8))(q, k, v)
        expect = _ref(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   rtol=2e-5, atol=2e-5)

    def test_chunked_grads_match(self, seq_mesh):
        q, k, v = _rand_qkv(b=2, s=64, h=2, d=8)  # S_loc=16, chunks of 4

        def loss_chunked(q, k, v):
            return (ring_attention(q, k, v, seq_mesh, causal=True,
                                   q_chunk=4) ** 2).sum()

        def loss_ref(q, k, v):
            return (_ref(q, k, v, True) ** 2).sum()

        g_c = jax.jit(jax.grad(loss_chunked, argnums=(0, 1, 2)))(q, k, v)
        g_r = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_c, g_r):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4)
