"""Attention kernel tests: flash (Pallas, interpreter mode on CPU) and ring
(shard_map over the seq axis) against the XLA reference — values and
gradients (SURVEY.md §5 long-context requirements)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_training_tpu.models.layers import dot_product_attention
from distributed_pytorch_training_tpu.ops import (
    flash_attention,
    make_flash_attention_fn,
    make_ring_attention_fn,
    ring_attention,
)
from distributed_pytorch_training_tpu.parallel import MeshSpec, build_mesh


def _rand_qkv(b=2, s=128, h=4, d=32, seed=0):
    rng = np.random.RandomState(seed)
    shape = (b, s, h, d)
    q = rng.randn(*shape).astype(np.float32) * 0.5
    k = rng.randn(*shape).astype(np.float32) * 0.5
    v = rng.randn(*shape).astype(np.float32) * 0.5
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


def _ref(q, k, v, causal):
    mask = None
    if causal:
        mask = jnp.tril(jnp.ones((q.shape[1], k.shape[1]), bool))[None, None]
    return dot_product_attention(q, k, v, mask=mask)


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, causal):
        q, k, v = _rand_qkv()
        out = flash_attention(q, k, v, causal, None, 64, 64)
        expect = _ref(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   rtol=2e-5, atol=2e-5)

    def test_uneven_blocks_auto_fit(self):
        # 100 has no divisor that is a multiple of 8, so the block picker
        # falls back to spanning the axis — still correct, never an error.
        q, k, v = _rand_qkv(s=100)
        out = flash_attention(q, k, v, False, None, 64, 64)
        expect = _ref(q, k, v, False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   rtol=2e-5, atol=2e-5)
        # 96 = 12 blocks of 8: picker takes the largest <=64 divisor (48).
        from distributed_pytorch_training_tpu.ops.flash_attention import (
            _fit_block,
        )
        assert _fit_block(64, 96) == 48
        assert _fit_block(64, 100) == 100
        assert _fit_block(512, 1024) == 512
        assert _fit_block(512, 384) == 384
        # degenerate divisors (8 | 2056 but grid would be 257 tiny tiles)
        # and sub-8 requests must not produce pathological kernels
        with pytest.raises(ValueError, match="block"):
            _fit_block(512, 2056)
        assert _fit_block(4, 2048) == 8
        assert _fit_block(512, 1032) == 344  # >= s//8 floor keeps the grid sane

    @pytest.mark.slow
    def test_gradients_match_reference(self):
        q, k, v = _rand_qkv(b=1, s=64, h=2, d=16)

        def loss_flash(q, k, v):
            return (flash_attention(q, k, v, True, None, 32, 32) ** 2).sum()

        def loss_ref(q, k, v):
            return (_ref(q, k, v, True) ** 2).sum()

        g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_flash, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4)


    @pytest.mark.slow
    def test_long_context_grad_parity_s4096(self):
        """S=4096 forward+backward through the blockwise Pallas kernels
        (interpreter mode) vs the XLA reference — the long-context bar from
        SURVEY.md §5. The r2 backward was an O(S^2) recompute; this exercises
        the real dq/dk/dv kernels at a length where the (S,S) score matrix
        (64 MB fp32 per head) would no longer be a reasonable residual."""
        q, k, v = _rand_qkv(b=1, s=4096, h=1, d=64, seed=3)

        def loss_flash(q, k, v):
            return (flash_attention(q, k, v, True, None, 512, 512) ** 2).sum()

        def loss_ref(q, k, v):
            return (_ref(q, k, v, True) ** 2).sum()

        g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g_flash, g_ref, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-3,
                err_msg=f"d{name} diverges at S=4096")

    @pytest.mark.slow
    def test_bf16_grad_parity(self):
        """bf16 inputs (the TPU compute dtype): kernel stats stay fp32, so
        grads must track the fp32-stat reference within bf16 tolerance."""
        q, k, v = _rand_qkv(b=1, s=256, h=2, d=32, seed=4)
        qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))

        def loss_flash(q, k, v):
            return (flash_attention(q, k, v, True, None, 128, 128)
                    .astype(jnp.float32) ** 2).sum()

        def loss_ref(q, k, v):
            return (_ref(q.astype(jnp.float32), k.astype(jnp.float32),
                         v.astype(jnp.float32), True) ** 2).sum()

        g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(qb, kb, vb)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(qb, kb, vb)
        for a, b in zip(g_flash, g_ref):
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                rtol=0.1, atol=0.5)

    def test_adapter_general_mask_falls_back_to_einsum(self):
        """A mask with (Sq, Sk) structure has no blockwise formulation here;
        the adapter must fall back to the XLA path (bit-equal), not error —
        the fast path narrowing to a ValueError on real data was r3 weak-#3."""
        fn = make_flash_attention_fn(causal=True)
        q, k, v = _rand_qkv(b=2, s=64)
        rng = np.random.RandomState(7)
        general = jnp.asarray(rng.rand(2, 1, 64, 64) > 0.3)
        out = fn(q, k, v, mask=general)
        cm = jnp.tril(jnp.ones((64, 64), bool))[None, None]
        expect = dot_product_attention(q, k, v, mask=general & cm)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(expect))


class TestFlashPaddingMask:
    """Key-padding masks ride the Pallas kernels (VERDICT r3 #2): BERT on
    real padded batches must keep the flash path, gradients included."""

    def _padded_mask(self, b, s, n_pad, front=False):
        valid = np.ones((b, s), np.float32)
        if front:
            valid[:, :n_pad] = 0.0  # all-masked FIRST blocks: the online
            # softmax accumulates p=1 garbage until the first live block
            # rescales it to 0 — the hard case for the m=NEG_INF init
        else:
            valid[:, s - n_pad:] = 0.0
        return jnp.asarray(valid)

    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("front", [False, True])
    def test_padded_forward_matches_reference(self, causal, front):
        q, k, v = _rand_qkv(b=2, s=128)
        kv_valid = self._padded_mask(2, 128, 40, front)
        out = flash_attention(q, k, v, causal, None, 64, 64, kv_valid)
        mask = kv_valid[:, None, None, :].astype(bool)
        if causal:
            mask = mask & jnp.tril(jnp.ones((128, 128), bool))[None, None]
        expect = dot_product_attention(q, k, v, mask=mask)
        valid_rows = np.asarray(kv_valid, bool) if causal else \
            np.ones((2, 128), bool)
        # padded-out query rows emit garbage by contract (loss zero-weights
        # them); compare only rows with at least one live key
        np.testing.assert_allclose(
            np.asarray(out)[valid_rows], np.asarray(expect)[valid_rows],
            rtol=2e-5, atol=2e-5)

    @pytest.mark.slow
    def test_padded_gradients_match_reference(self):
        """Grad parity under the real contract: the loss zero-weights padded
        query rows, so their garbage output contributes no cotangent."""
        q, k, v = _rand_qkv(b=2, s=128, h=2, d=16, seed=5)
        kv_valid = self._padded_mask(2, 128, 48)
        w = kv_valid[:, :, None, None]  # zero-weight padded query rows

        def loss_flash(q, k, v):
            out = flash_attention(q, k, v, False, None, 64, 64, kv_valid)
            return ((out * w) ** 2).sum()

        def loss_ref(q, k, v):
            mask = kv_valid[:, None, None, :].astype(bool)
            return ((dot_product_attention(q, k, v, mask=mask) * w) ** 2).sum()

        g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g_flash, g_ref, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4,
                                       err_msg=f"d{name} diverges (padded)")
        # no gradient may leak into padded K/V positions
        pad = np.asarray(kv_valid) == 0
        for g, name in ((g_flash[1], "dk"), (g_flash[2], "dv")):
            leaked = np.abs(np.asarray(g)[pad]).max()
            assert leaked < 1e-6, f"{name} leaks {leaked} into padding"

    @pytest.mark.slow
    def test_long_context_padded_grad_parity_s4096(self):
        """The S=4096 grad-parity bar from r2/r3, now with padded rows
        (VERDICT r3 #2's done-criterion)."""
        q, k, v = _rand_qkv(b=1, s=4096, h=1, d=64, seed=6)
        kv_valid = self._padded_mask(1, 4096, 512)
        w = kv_valid[:, :, None, None]

        def loss_flash(q, k, v):
            out = flash_attention(q, k, v, True, None, 512, 512, kv_valid)
            return ((out * w) ** 2).sum()

        def loss_ref(q, k, v):
            mask = kv_valid[:, None, None, :].astype(bool) & \
                jnp.tril(jnp.ones((4096, 4096), bool))[None, None]
            return ((dot_product_attention(q, k, v, mask=mask) * w) ** 2).sum()

        g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g_flash, g_ref, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-3,
                err_msg=f"d{name} diverges at S=4096 (padded)")

    def test_adapter_padding_mask_takes_kernel_path(self):
        """The (B, 1, 1, Sk) padding_mask form must ride the kernel, and
        match the einsum path on the valid rows."""
        from distributed_pytorch_training_tpu.models.layers import padding_mask

        q, k, v = _rand_qkv(b=2, s=64)
        am = self._padded_mask(2, 64, 16)
        fn = make_flash_attention_fn(causal=False, block_q=32, block_k=32)
        out = fn(q, k, v, mask=padding_mask(am))
        expect = dot_product_attention(q, k, v, mask=padding_mask(am))
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   rtol=2e-5, atol=2e-5)


class TestRingAttention:
    @pytest.fixture(scope="class")
    def seq_mesh(self, devices):
        return build_mesh(MeshSpec(data=2, seq=4), devices=devices)

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, seq_mesh, causal):
        q, k, v = _rand_qkv(b=2, s=64, h=2, d=16)
        out = jax.jit(lambda q, k, v: ring_attention(
            q, k, v, seq_mesh, causal=causal))(q, k, v)
        expect = _ref(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   rtol=2e-5, atol=2e-5)

    @pytest.mark.slow
    def test_gradients_flow_through_ring(self, seq_mesh):
        q, k, v = _rand_qkv(b=2, s=32, h=2, d=8)

        def loss_ring(q, k, v):
            return (ring_attention(q, k, v, seq_mesh, causal=True) ** 2).sum()

        def loss_ref(q, k, v):
            return (_ref(q, k, v, True) ** 2).sum()

        g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ring, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4)

    def test_seq_axis_1_degrades_gracefully(self, devices):
        # mesh with seq=1: ring of length 1 == plain attention
        mesh = build_mesh(MeshSpec(data=2), devices=devices[:2])
        q, k, v = _rand_qkv(b=2, s=32, h=2, d=8)
        out = jax.jit(lambda q, k, v: ring_attention(
            q, k, v, mesh, causal=True))(q, k, v)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(_ref(q, k, v, True)),
                                   rtol=2e-5, atol=2e-5)


class TestModelKernelIntegration:
    """Kernel plumbing THROUGH a real GPT2LMHead (mask routing, adapter
    dispatch, logits parity) — the property is architecture-independent, so
    a shrunk gpt2_124m keeps these in the FAST set (the full-size variants
    cost 1-2 min each in interpreter mode and tested nothing extra)."""

    TINY = dict(depth=2, hidden_dim=128, num_heads=2, vocab_size=1000)

    def test_gpt2_flash_matches_xla(self):
        from distributed_pytorch_training_tpu.models import get_model

        ids = jnp.asarray(np.random.RandomState(0).randint(0, 1000, (2, 64)))
        m_xla = get_model("gpt2_124m", max_position=64, **self.TINY)
        variables = m_xla.init(jax.random.PRNGKey(0), ids, train=False)
        out_xla = m_xla.apply(variables, ids, train=False)

        m_flash = get_model("gpt2_124m", max_position=64, **self.TINY,
                            attention_fn=make_flash_attention_fn(
                                causal=True, block_q=32, block_k=32))
        out_flash = m_flash.apply(variables, ids, train=False)
        np.testing.assert_allclose(np.asarray(out_xla), np.asarray(out_flash),
                                   rtol=3e-4, atol=3e-4)

    def test_gpt2_flash_with_padding_mask_matches_xla(self):
        """Padded batches keep the flash path end-to-end through the model
        (r3 weak-#3: the fast path used to narrow exactly where real data
        begins). Valid-position logits must match the einsum path."""
        from distributed_pytorch_training_tpu.models import get_model

        rng = np.random.RandomState(1)
        ids = jnp.asarray(rng.randint(0, 1000, (2, 64)))
        am = np.ones((2, 64), np.float32)
        am[:, 48:] = 0.0
        am = jnp.asarray(am)

        m_xla = get_model("gpt2_124m", max_position=64, **self.TINY)
        variables = m_xla.init(jax.random.PRNGKey(0), ids, train=False)
        out_xla = m_xla.apply(variables, ids, attention_mask=am, train=False)

        m_flash = get_model("gpt2_124m", max_position=64, **self.TINY,
                            attention_fn=make_flash_attention_fn(
                                causal=True, block_q=32, block_k=32))
        out_flash = m_flash.apply(variables, ids, attention_mask=am,
                                  train=False)
        valid = np.asarray(am, bool)
        np.testing.assert_allclose(np.asarray(out_xla)[valid],
                                   np.asarray(out_flash)[valid],
                                   rtol=3e-4, atol=3e-4)

    def test_gpt2_ring_path_still_rejects_padding_mask(self):
        from distributed_pytorch_training_tpu.models import get_model

        ids = jnp.zeros((8, 32), jnp.int32)
        m = get_model("gpt2_124m", max_position=32, **self.TINY,
                      attention_fn=make_ring_attention_fn(
                          build_mesh(MeshSpec(data=8)), causal=True))
        variables = m.init(jax.random.PRNGKey(0), ids, train=False)
        with pytest.raises(ValueError, match="mask"):
            m.apply(variables, ids, attention_mask=jnp.ones((8, 32)),
                    train=False)


class TestRingFlashFused:
    """The fused ring+flash path (VERDICT r3 #4): each ring step runs the
    Pallas blockwise kernel (interpreter mode on CPU), partials merge via
    fp32 lse, the backward re-runs the ring with the flash grad kernels.
    Must be numerically interchangeable with the einsum ring."""

    @pytest.fixture(scope="class")
    def seq_mesh(self, devices):
        return build_mesh(MeshSpec(data=2, seq=4), devices=devices)

    @pytest.mark.parametrize("causal", [False, True])
    def test_fused_matches_reference(self, seq_mesh, causal):
        q, k, v = _rand_qkv(b=2, s=128, h=2, d=16)  # S_loc=32
        out = jax.jit(lambda q, k, v: ring_attention(
            q, k, v, seq_mesh, causal=causal, use_pallas=True,
            block_q=32, block_k=32))(q, k, v)
        expect = _ref(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   rtol=2e-5, atol=2e-5)

    @pytest.mark.slow
    def test_fused_gradients_match_reference(self, seq_mesh):
        q, k, v = _rand_qkv(b=2, s=64, h=2, d=8, seed=2)  # S_loc=16

        def loss_fused(q, k, v):
            return (ring_attention(q, k, v, seq_mesh, causal=True,
                                   use_pallas=True, block_q=16,
                                   block_k=16) ** 2).sum()

        def loss_ref(q, k, v):
            return (_ref(q, k, v, True) ** 2).sum()

        g_f = jax.jit(jax.grad(loss_fused, argnums=(0, 1, 2)))(q, k, v)
        g_r = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g_f, g_r, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4,
                                       err_msg=f"d{name} (fused ring)")

    def test_fused_path_runs_pallas_kernels(self, seq_mesh):
        """The point of the fusion: the compiled step must contain the
        Pallas kernel, not the einsum formulation (r3 weak-#4: 'flash
        speed and ring scale-out don't compose')."""
        q, k, v = _rand_qkv(b=2, s=128, h=2, d=16)

        def count_pallas(jaxpr):
            n = 0
            for eqn in jaxpr.eqns:
                if eqn.primitive.name == "pallas_call":
                    n += 1
                # fun_jaxpr: custom_vjp_call_jaxpr's body param on jax
                # 0.4.x — without it the fused ring's kernels (inside the
                # _ring_flash custom_vjp) are invisible to this census
                for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
                    sub = eqn.params.get(key) if eqn.params else None
                    if sub is not None:
                        n += count_pallas(getattr(sub, "jaxpr", sub))
                for key in ("branches",):
                    for s in (eqn.params.get(key) or ()):
                        n += count_pallas(getattr(s, "jaxpr", s))
            return n

        fused = jax.make_jaxpr(lambda q, k, v: ring_attention(
            q, k, v, seq_mesh, causal=True, use_pallas=True,
            block_q=32, block_k=32))(q, k, v)
        einsum = jax.make_jaxpr(lambda q, k, v: ring_attention(
            q, k, v, seq_mesh, causal=True, use_pallas=False))(q, k, v)
        assert count_pallas(fused.jaxpr) > 0
        assert count_pallas(einsum.jaxpr) == 0

    def test_auto_selection_logic(self, seq_mesh):
        """On CPU backends auto must pick the einsum path (pallas would run
        in interpreter mode — pure overhead); the TPU decision is
        flash_supports_length on the SHARD length."""
        from distributed_pytorch_training_tpu.ops.flash_attention import (
            flash_backend_supported,
        )

        assert not flash_backend_supported()  # test backend is CPU
        q, k, v = _rand_qkv(b=2, s=128, h=2, d=16)
        jaxpr = jax.make_jaxpr(lambda q, k, v: ring_attention(
            q, k, v, seq_mesh, causal=True))(q, k, v)  # use_pallas=None
        assert "pallas_call" not in str(jaxpr)


class TestRingAttentionChunked:
    """The q-chunked ring body (bounded per-step score memory) must be a
    pure memory trade: same values, same grads as the straight-through
    block — exercised by forcing q_chunk below the shard length."""

    @pytest.fixture(scope="class")
    def seq_mesh(self, devices):
        return build_mesh(MeshSpec(data=2, seq=4), devices=devices)

    @pytest.mark.parametrize("causal", [False, True])
    def test_chunked_matches_reference(self, seq_mesh, causal):
        q, k, v = _rand_qkv(b=2, s=128, h=2, d=16)  # S_loc=32, chunks of 8
        out = jax.jit(lambda q, k, v: ring_attention(
            q, k, v, seq_mesh, causal=causal, q_chunk=8))(q, k, v)
        expect = _ref(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   rtol=2e-5, atol=2e-5)

    def test_chunked_grads_match(self, seq_mesh):
        q, k, v = _rand_qkv(b=2, s=64, h=2, d=8)  # S_loc=16, chunks of 4

        def loss_chunked(q, k, v):
            return (ring_attention(q, k, v, seq_mesh, causal=True,
                                   q_chunk=4) ** 2).sum()

        def loss_ref(q, k, v):
            return (_ref(q, k, v, True) ** 2).sum()

        g_c = jax.jit(jax.grad(loss_chunked, argnums=(0, 1, 2)))(q, k, v)
        g_r = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_c, g_r):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4)
