"""Worker process for the 2-process multi-host test (test_multihost.py).

Runs as one of DPT_NUM_PROCESSES=2 processes on the CPU backend, each with 2
virtual local devices — the smallest honest model of a 2-host TPU pod slice
(the env:// rendezvous contract of /root/reference/train_ddp.py:53-68).
Every assertion here runs in BOTH processes; any failure exits non-zero and
the parent test fails.
"""

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 2)
except AttributeError:
    # Older jax: the option doesn't exist; fall back to the XLA flag (must
    # land before the backend initializes).
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=2").strip()

import flax.linen as nn
import jax.numpy as jnp
import numpy as np


def main() -> None:
    from distributed_pytorch_training_tpu.parallel import (
        MeshSpec, barrier, broadcast_from_main, build_mesh, host_all_gather,
        shard_batch,
    )
    from distributed_pytorch_training_tpu.parallel.collectives import (
        reduce_scalar,
    )
    from distributed_pytorch_training_tpu.runtime import (
        cleanup_distributed, per_process_seed, setup_distributed,
    )

    ctx = setup_distributed()
    rank = ctx.process_index

    # runtime topology: 2 processes x 2 local devices = 4 global
    assert ctx.process_count == 2, ctx
    assert ctx.local_device_count == 2, ctx
    assert ctx.device_count == 4, ctx
    assert ctx.is_main == (rank == 0)
    assert per_process_seed(42) == 42 + rank  # ref :76-78 rule, live runtime

    # host-level collectives (the dist.barrier / rank-0 broadcast surface)
    barrier("start")
    got = broadcast_from_main(np.float32(123.0 + 7 * rank))
    assert float(got) == 123.0, got  # everyone sees process 0's value

    total = reduce_scalar(rank + 1, op="sum")  # 1 + 2
    assert total == 3.0, total
    gathered = np.asarray(host_all_gather(np.float32(rank)))
    np.testing.assert_array_equal(np.sort(gathered.ravel()), [0.0, 1.0])

    # 2-process shard_batch -> sharded TRAIN step over the global mesh
    mesh = build_mesh(MeshSpec(data=4))
    global_batch, local_batch = 8, 4

    class TinyNet(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            x = x.reshape(x.shape[0], -1)
            x = nn.gelu(nn.Dense(16)(x))
            return nn.Dense(10)(x)

    from distributed_pytorch_training_tpu.training import TrainConfig, Trainer
    from distributed_pytorch_training_tpu.training.optim import sgd
    from distributed_pytorch_training_tpu.training.tasks import (
        ImageClassificationTask,
    )

    task = ImageClassificationTask(mean=(0.5, 0.5, 0.5), std=(0.25, 0.25, 0.25),
                                   augment=False)
    trainer = Trainer(task, mesh, TrainConfig(seed=0))
    state = trainer.init_state(TinyNet(), np.zeros((1, 8, 8, 3), np.float32),
                               sgd(0.1), jax.random.PRNGKey(0))

    # every process contributes ITS OWN slice of the global batch (the
    # multi-host generalization of DistributedSampler, ref :122-127) — and
    # the data is rank-dependent, so a correct global reduction must see both
    rng = np.random.RandomState(100 + rank)
    local = {
        "image": rng.randint(0, 256, (local_batch, 8, 8, 3)).astype(np.uint8),
        "label": rng.randint(0, 10, local_batch).astype(np.int32),
        "weight": np.ones(local_batch, np.float32),
    }
    batch = shard_batch(local, mesh)
    assert batch["image"].shape[0] == global_batch  # global view
    # this process holds only its local shard's rows
    own = sum(int(np.prod(s.data.shape[:1]))
              for s in batch["image"].addressable_shards)
    assert own == local_batch, own

    losses = []
    key = jax.random.PRNGKey(1)
    for _ in range(4):
        state, metrics = trainer._train_step(state, batch, key)
        # metrics are replicated => identical on both processes
        w = float(jax.device_get(metrics["weight"]))
        assert w == global_batch, w
        losses.append(float(jax.device_get(metrics["loss_sum"])) / w)
    assert np.isfinite(losses).all(), losses
    assert losses[-1] < losses[0], losses

    # the loss is a global quantity: both ranks must agree bit-for-bit
    all_losses = np.asarray(host_all_gather(np.float32(losses[-1])))
    assert np.all(all_losses == all_losses.ravel()[0]), all_losses

    barrier("end")
    cleanup_distributed()
    print(f"WORKER_OK rank={rank} loss={losses[-1]:.5f}", flush=True)


if __name__ == "__main__":
    main()
