"""Device-time attribution plane (ISSUE 15): the re-armable StepProfiler
(on-demand windows, busy refusal, session guard), the trace ->
``device_profile`` ingestion (telemetry/device.py over
trace_analysis.device_time_split), the ``POST /profile`` endpoint, the
anomaly-triggered capture path through the REAL instrumented train loop on
the CPU mesh, the straggler detector's device attribution, and the
federated /metrics fan-in.
"""

import gzip
import json
import urllib.error
import urllib.request
from pathlib import Path

import jax
import jax.numpy as jnp
import pytest

from distributed_pytorch_training_tpu import telemetry
from distributed_pytorch_training_tpu.telemetry import device as tele_device
from distributed_pytorch_training_tpu.utils.profiling import (
    StepProfiler, session_owner, trace_session,
)


@pytest.fixture(autouse=True)
def _clean_global_recorder():
    telemetry.reset()
    yield
    telemetry.reset()
    # a leaked jax profiler session would poison every later test
    assert session_owner() is None


@pytest.fixture
def counted_profiler(monkeypatch):
    """jax.profiler start/stop replaced by counters (the
    test_training.py lifecycle-suite convention): session bookkeeping is
    the subject, and an imbalance must fail the test, not poison the
    process's real profiler."""
    calls = {"start": 0, "stop": 0, "dirs": []}

    def _start(log_dir, **kw):
        calls["start"] += 1
        calls["dirs"].append(str(log_dir))

    monkeypatch.setattr(jax.profiler, "start_trace", _start)
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: calls.__setitem__("stop",
                                                  calls["stop"] + 1))
    return calls


def _scrape(port, path="/metrics"):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=2) as resp:
        return resp.status, resp.read().decode("utf-8")


def _post(port, path):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                                 method="POST")
    with urllib.request.urlopen(req, timeout=2) as resp:
        return resp.status, resp.read().decode("utf-8")


# ---------------------------------------------------------------------------
# device_time_split on hand-built traces
# ---------------------------------------------------------------------------


def _write_trace(tmp_path, events, pid_names=None, tid_names=None):
    """A synthetic *.trace.json.gz in the layout jax.profiler writes."""
    trace = []
    for pid, name in (pid_names or {}).items():
        trace.append({"ph": "M", "pid": pid, "name": "process_name",
                      "args": {"name": name}})
    for (pid, tid), name in (tid_names or {}).items():
        trace.append({"ph": "M", "pid": pid, "tid": tid,
                      "name": "thread_name", "args": {"name": name}})
    for name, pid, tid, ts, dur in events:
        trace.append({"ph": "X", "pid": pid, "tid": tid, "name": name,
                      "ts": ts, "dur": dur})
    d = tmp_path / "plugins" / "profile" / "2026_08_04"
    d.mkdir(parents=True)
    with gzip.open(d / "host.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": trace}, f)
    return str(tmp_path)


class TestDeviceTimeSplit:
    def test_four_way_split_sums_to_window(self, tmp_path):
        """compute + hidden + exposed + gap == window, with a collective
        half-hidden under compute and a host gap between ops."""
        from distributed_pytorch_training_tpu.experiments.trace_analysis \
            import device_time_split

        log = _write_trace(
            tmp_path,
            # compute [0, 100), all-reduce [50, 150) -> 50 hidden /
            # 50 exposed; compute [250, 300) after a 100us host gap
            [("fusion.1", 7, 1, 0.0, 100.0),
             ("all-reduce.2", 7, 1, 50.0, 100.0),
             ("fusion.3", 7, 1, 250.0, 50.0)],
            pid_names={7: "/device:TPU:0 (abc)"},
            tid_names={(7, 1): "XLA Ops"})
        s = device_time_split(log)
        assert s["window_us"] == 300.0
        assert s["comm_hidden_us"] == 50.0
        assert s["comm_exposed_us"] == 50.0
        assert s["compute_us"] == 100.0     # 150 busy-union minus comm
        assert s["host_gap_us"] == 100.0
        assert (s["compute_us"] + s["comm_hidden_us"]
                + s["comm_exposed_us"] + s["host_gap_us"]) \
            == s["window_us"]
        assert s["by_op"] == {"all-reduce": 100.0}
        assert s["exposed_frac_pct"] == 50.0

    def test_cpu_thunk_lanes_and_wrapped_names(self, tmp_path):
        """The CPU test backend's shape: no device pids, wrapped_ thunk
        names, runtime bookkeeping excluded."""
        from distributed_pytorch_training_tpu.experiments.trace_analysis \
            import device_time_split

        log = _write_trace(
            tmp_path,
            [("wrapped_dot.1", 1, 1, 0.0, 80.0),
             ("wrapped_all-gather.2", 1, 2, 80.0, 20.0),
             ("ThunkExecutor bookkeeping", 1, 3, 0.0, 500.0)])
        s = device_time_split(log)
        assert s["window_us"] == 100.0
        assert s["compute_us"] == 80.0
        assert s["comm_exposed_us"] == 20.0
        assert s["comm_hidden_us"] == 0.0
        assert s["host_gap_us"] == 0.0
        assert s["by_op"] == {"all-gather": 20.0}


# ---------------------------------------------------------------------------
# the re-armable StepProfiler
# ---------------------------------------------------------------------------


class TestStepProfilerRearm:
    def test_armed_window_opens_closes_and_ingests(self, tmp_path,
                                                   counted_profiler):
        captures = []
        prof = StepProfiler(str(tmp_path),
                            on_capture=lambda d, info: captures.append(
                                (d, info)))
        assert prof.request_capture(2, reason="http") is True
        prof(0)   # opens at the next step
        assert counted_profiler["start"] == 1
        prof(1)
        prof(2)   # closes: 2 steps elapsed
        assert counted_profiler == {
            "start": 1, "stop": 1,
            "dirs": counted_profiler["dirs"]}
        assert len(captures) == 1
        d, info = captures[0]
        assert d == counted_profiler["dirs"][0]
        assert info["start_step"] == 0 and info["stop_step"] == 2
        assert info["reason"] == "http"
        # re-armable: a SECOND window in the same run
        assert prof.request_capture(1, reason="again") is True
        prof(3)
        prof(4)
        assert counted_profiler["start"] == 2
        assert counted_profiler["stop"] == 2
        assert len(captures) == 2
        # distinct capture directories — sessions never mix
        assert counted_profiler["dirs"][0] != counted_profiler["dirs"][1]

    def test_busy_refusal_counts_not_clobbers(self, tmp_path,
                                              counted_profiler):
        rec = telemetry.configure(str(tmp_path / "t.jsonl"))
        prof = StepProfiler(str(tmp_path))
        assert prof.request_capture(4) is True
        assert prof.request_capture(2) is False   # already armed
        prof(0)                                   # window opens
        assert prof.request_capture(2) is False   # in flight
        assert prof.busy_refused == 2
        busy = [e for e in rec.tail(50)
                if e["kind"] == "counter" and e["name"] == "profiler_busy"]
        assert len(busy) == 2
        prof.close()
        assert counted_profiler["start"] == counted_profiler["stop"] == 1

    def test_busy_while_static_window_open(self, tmp_path,
                                           counted_profiler):
        prof = StepProfiler(str(tmp_path), 0, 5)
        prof(0)   # static window opens
        assert prof._active
        assert prof.request_capture(2) is False
        prof.close()

    def test_capture_context_and_nested_refusal(self, tmp_path,
                                                counted_profiler):
        captures = []
        prof = StepProfiler(str(tmp_path),
                            on_capture=lambda d, i: captures.append(i))
        with prof.capture(reason="bench") as d:
            assert d is not None
            with prof.capture() as d2:   # nested: refused, still runs
                assert d2 is None
        assert counted_profiler["start"] == counted_profiler["stop"] == 1
        assert [c["reason"] for c in captures] == ["bench"]
        assert session_owner() is None

    def test_trace_session_guard_refuses_second(self, tmp_path,
                                                counted_profiler):
        with trace_session(str(tmp_path / "a")) as started:
            assert started is True
            with trace_session(str(tmp_path / "b")) as second:
                assert second is False
        assert counted_profiler["start"] == counted_profiler["stop"] == 1
        assert session_owner() is None

    def test_close_mid_armed_window_fires_once(self, tmp_path,
                                               counted_profiler):
        captures = []
        prof = StepProfiler(str(tmp_path),
                            on_capture=lambda d, i: captures.append(i))
        prof.request_capture(10)
        prof(0)
        prof.close()
        prof.close()   # idempotent
        assert counted_profiler["start"] == counted_profiler["stop"] == 1
        assert len(captures) == 1
        # honest truncation: the window spanned ONE hook call, not the
        # requested 10 — steps/stop_step report what actually happened
        # (a fabricated K would overstate measured MFU by K/elapsed)
        assert captures[0]["steps"] == 1
        assert captures[0]["stop_step"] == captures[0]["start_step"] + 1

    def test_window_step_labels_survive_label_resets(self, tmp_path,
                                                     counted_profiler):
        """Armed windows stamp start_step from the label passed in and
        derive stop_step from ELAPSED hook calls — a mid-window label
        reset (the epoch boundary: step_hook labels restart) cannot
        produce stop < start or a negative step count."""
        captures = []
        prof = StepProfiler(str(tmp_path),
                            on_capture=lambda d, i: captures.append(i))
        prof.request_capture(2)
        prof(18)    # window opens at global step 18 (end of an epoch)
        prof(19)
        prof(0)     # next epoch: labels reset; window closes here
        assert captures and captures[0]["start_step"] == 18
        assert captures[0]["stop_step"] == 20
        assert captures[0]["steps"] == 2

    def test_nonzero_process_refuses_arming(self, tmp_path, monkeypatch,
                                            counted_profiler):
        """Only process 0 opens windows (__call__ returns early
        elsewhere) — accepting an arm on another rank would wedge its
        profiler on a pending that can never fire (every later POST
        would 409 forever)."""
        monkeypatch.setattr(jax, "process_index", lambda: 1)
        prof = StepProfiler(str(tmp_path))
        assert prof.request_capture(2) is False
        prof(0)
        assert prof._pending is None and prof._window is None
        assert counted_profiler["start"] == 0

    def test_capture_budget_bounds_disk(self, tmp_path, counted_profiler):
        prof = StepProfiler(str(tmp_path), max_captures=1)
        assert prof.request_capture(1) is True
        prof(0)
        prof(1)
        assert prof.request_capture(1) is False   # budget spent
        assert counted_profiler["start"] == counted_profiler["stop"] == 1

    def test_refused_capture_does_not_burn_budget(self, tmp_path,
                                                  counted_profiler):
        """A capture refused because another component holds the jax
        session must not consume a budget slot — N refusals would
        otherwise exhaust max_captures with zero traces written."""
        prof = StepProfiler(str(tmp_path), max_captures=2)
        with trace_session(str(tmp_path / "other")) as started:
            assert started
            for _ in range(5):
                with prof.capture() as d:
                    assert d is None   # refused: session held elsewhere
        with prof.capture() as d:      # budget intact
            assert d is not None
        assert prof._n_captures == 1

    def test_broken_ingestor_never_raises(self, tmp_path,
                                          counted_profiler):
        def boom(d, info):
            raise RuntimeError("ingestor broke")

        prof = StepProfiler(str(tmp_path), on_capture=boom)
        prof.request_capture(1)
        prof(0)
        prof(1)   # on_capture fires here — contained
        assert counted_profiler["stop"] == 1


# ---------------------------------------------------------------------------
# POST /profile + identity + device series on the metrics endpoint
# ---------------------------------------------------------------------------


class TestProfileEndpoint:
    def test_post_profile_arms_busy_and_missing(self, tmp_path):
        rec = telemetry.configure(str(tmp_path / "t.jsonl"), gen=2, rank=1)
        server = telemetry.MetricsServer(0, recorder=rec, backend="cpu")
        port = server.start()
        try:
            # no profiler wired yet -> 404
            with pytest.raises(urllib.error.HTTPError) as err:
                _post(port, "/profile?steps=2")
            assert err.value.code == 404
            got = []
            server.profile_handler = lambda steps: (got.append(steps)
                                                    or True)
            status, body = _post(port, "/profile?steps=3")
            assert status == 202 and json.loads(body)["armed"] is True
            assert got == [3]
            server.profile_handler = lambda steps: False   # busy
            with pytest.raises(urllib.error.HTTPError) as err:
                _post(port, "/profile?steps=2")
            assert err.value.code == 409
            for bad in ("steps=0", "steps=nope"):
                with pytest.raises(urllib.error.HTTPError) as err:
                    _post(port, f"/profile?{bad}")
                assert err.value.code == 400
            with pytest.raises(urllib.error.HTTPError) as err:
                _post(port, "/elsewhere")
            assert err.value.code == 404
        finally:
            server.stop()

    def test_build_info_and_healthz_identity(self, tmp_path):
        rec = telemetry.configure(str(tmp_path / "t.jsonl"), gen=4, rank=2)
        server = telemetry.MetricsServer(0, recorder=rec, backend="tpu")
        port = server.start()
        try:
            _, body = _scrape(port)
            assert ('dpt_build_info{gen="4",rank="2",schema_version="2",'
                    'backend="tpu"} 1') in body
            rec.span_event("step_dispatch", 0.004, step=0)
            status, hz = _scrape(port, "/healthz")
            detail = json.loads(hz)
            assert (detail["gen"], detail["rank"]) == (4, 2)
            assert detail["schema_version"] == telemetry.SCHEMA_VERSION
            assert detail["backend"] == "tpu"
        finally:
            server.stop()

    def test_device_profile_events_become_series(self, tmp_path):
        rec = telemetry.configure(str(tmp_path / "t.jsonl"))
        server = telemetry.MetricsServer(0, recorder=rec)
        port = server.start()
        try:
            rec.emit("device_profile", "device_profile",
                     compute_ms=900.0, comm_hidden_ms=50.0,
                     comm_exposed_ms=40.0, host_gap_ms=10.0,
                     window_ms=1000.0, exposed_comm_ratio=0.444)
            _, body = _scrape(port)
            assert "dpt_device_profiles_total 1" in body
            assert 'dpt_device_seconds{phase="compute"} 0.900000' in body
            assert ('dpt_device_seconds{phase="comm_exposed"} 0.040000'
                    in body)
            assert "dpt_exposed_comm_ratio 0.444" in body
        finally:
            server.stop()


# ---------------------------------------------------------------------------
# anomaly-triggered capture: the watchdog's hook
# ---------------------------------------------------------------------------


class TestWatchdogCaptureHook:
    def _watchdog(self, hook, **kw):
        return telemetry.AnomalyWatchdog(
            min_samples=2, stall_factor=3.0, stall_min_s=0.4,
            spike_factor=3.0, capture_hook=hook, **kw)

    def test_stall_and_spike_arm_a_capture(self):
        armed = []
        wd = self._watchdog(lambda name, step: armed.append((name, step)))
        for i in range(4):
            wd.observe_step(i, 0.01, data_wait_s=0.001)
        wd.observe_step(4, 1.0, data_wait_s=0.9)     # loader stall
        for i in range(5, 10):
            wd.observe_step(i, 0.01, data_wait_s=0.001)
        wd.observe_step(10, 0.5, data_wait_s=0.001)  # busy-time spike
        assert armed == [("loader_stall", 4), ("step_time_spike", 10)]

    def test_non_finite_loss_does_not_arm(self):
        armed = []
        wd = self._watchdog(lambda name, step: armed.append(name))
        wd.observe_loss(3, float("nan"))
        assert wd.anomalies and not armed

    def test_hook_fires_before_abort_and_is_contained(self):
        armed = []

        def hook(name, step):
            armed.append(name)
            raise RuntimeError("broken hook")

        wd = self._watchdog(hook, abort=True)
        for i in range(3):
            wd.observe_step(i, 0.01, data_wait_s=0.001)
        with pytest.raises(telemetry.AnomalyAbort):
            wd.observe_step(3, 1.0, data_wait_s=0.9)
        assert armed == ["loader_stall"]   # armed despite abort + raise

    def test_absolute_stall_bound_fires_without_warmup(self):
        """The first post-resume step's stall (the fleet's gen-2 shape):
        the rolling median has nothing to compare against, and only the
        absolute bound can name it. Off by default — PR 8 semantics
        unchanged without the knob."""
        armed = []
        wd = telemetry.AnomalyWatchdog(
            stall_abs_s=1.0,
            capture_hook=lambda name, step: armed.append((name, step)))
        wd.observe_step(0, 1.6, data_wait_s=1.5)   # step 0: zero samples
        assert [a[0] for a in wd.anomalies] == ["loader_stall"]
        assert wd.anomalies[0][1]["absolute_bound_s"] == 1.0
        assert armed == [("loader_stall", 0)]
        # default watchdog: the same first-step stall stays invisible
        # (warm-up), exactly as before
        wd2 = telemetry.AnomalyWatchdog()
        wd2.observe_step(0, 1.6, data_wait_s=1.5)
        assert wd2.anomalies == []

    def test_kwargs_from_env(self, monkeypatch):
        from distributed_pytorch_training_tpu.telemetry.watchdog import (
            kwargs_from_env,
        )

        monkeypatch.setenv("DPT_WATCHDOG_MIN_SAMPLES", "3")
        monkeypatch.setenv("DPT_WATCHDOG_STALL_MIN_S", "0.25")
        monkeypatch.setenv("DPT_WATCHDOG_STALL_ABS_S", "1.5")
        monkeypatch.setenv("DPT_WATCHDOG_SPIKE_FACTOR", "junk")
        kw = kwargs_from_env()
        assert kw == {"min_samples": 3, "stall_min_s": 0.25,
                      "stall_abs_s": 1.5}
        assert telemetry.AnomalyWatchdog(**kw).min_samples == 3


# ---------------------------------------------------------------------------
# the CPU-mesh capture path end to end (ISSUE 15 acceptance)
# ---------------------------------------------------------------------------


class TestAnomalyCaptureEndToEnd:
    def test_stall_triggers_capture_and_device_attribution(self, tmp_path,
                                                           mesh8, capsys):
        """Through the REAL instrumented train loop: an injected
        loader_stall trips the watchdog, the watchdog arms a 2-step
        capture, a real jax.profiler trace is taken WHILE the run
        continues, and ingestion leaves a ``device_profile`` event whose
        split is self-consistent; ``telemetry summary`` renders the
        device block, and the fleet aggregator device-attributes the
        straggler it already names (span fallback intact for the clean
        peer)."""
        from distributed_pytorch_training_tpu.data.loader import (
            ShardedLoader,
        )
        from distributed_pytorch_training_tpu.resilience.__main__ import (
            _build_rig,
        )
        from distributed_pytorch_training_tpu.resilience.faults import (
            FaultInjector, FaultPlan,
        )
        from distributed_pytorch_training_tpu.telemetry.__main__ import (
            main as telemetry_main,
        )
        from distributed_pytorch_training_tpu.telemetry.aggregate import (
            aggregate_streams,
        )

        x = jnp.ones((64, 64), jnp.float32)
        mm = jax.jit(lambda a: (a @ a).sum())
        mm(x).block_until_ready()   # compile OUTSIDE any capture window

        def fake_step(state, batch, key):
            return state, {"loss_sum": mm(x),
                           "correct": jnp.float32(1.0),
                           "weight": jnp.float32(16.0)}

        def run_child(gen, stream_path, fault_hook=None, arm=False):
            trainer, _, loader = _build_rig(
                mesh8, seed=0, dataset_size=320, per_device_batch=2)
            trainer._train_step = fake_step
            if fault_hook is not None:
                loader = ShardedLoader(loader.dataset, trainer.mesh, 2,
                                       shuffle=True, seed=0,
                                       fault_hook=fault_hook)
            telemetry.configure(str(stream_path), gen=gen, rank=0)
            profiler = None
            if arm:
                profiler = StepProfiler(
                    str(tmp_path / f"prof{gen}"),
                    on_capture=tele_device.make_ingestor())
                # spike_factor high: CPU scheduling noise must not arm a
                # second (legitimate) spike capture under test
                trainer.watchdog = telemetry.AnomalyWatchdog(
                    min_samples=2, stall_factor=3.0, stall_min_s=0.4,
                    spike_factor=200.0,
                    capture_hook=lambda name, step:
                        profiler.request_capture(
                            2, reason=f"anomaly:{name}",
                            trigger_step=step))
            spe = len(loader)
            with profiler if profiler is not None else \
                    __import__("contextlib").nullcontext():
                trainer.train_epoch(None, loader.epoch(0), 0, spe,
                                    samples_per_step=[16] * spe,
                                    step_hook=profiler)
            telemetry.reset()

        p0 = tmp_path / "clean.jsonl"
        p1 = tmp_path / "stalled.jsonl"
        run_child(0, p0)
        injector = FaultInjector(
            FaultPlan.parse("loader_stall@step=8:0.6s"))
        run_child(1, p1, fault_hook=injector.on_loader_batch, arm=True)
        assert injector.fired == ["loader_stall@step=8:0.6s"]

        events = [json.loads(line) for line in
                  p1.read_text().splitlines()]
        anomalies = [e for e in events if e["kind"] == "anomaly"]
        assert any(a["name"] == "loader_stall" and a["step"] == 8
                   for a in anomalies)
        profiles = [e for e in events if e["kind"] == "device_profile"]
        stall_profiles = [e for e in profiles
                          if e["reason"] == "anomaly:loader_stall"]
        assert len(stall_profiles) == 1, profiles
        dp = stall_profiles[0]
        assert dp["trigger_step"] == 8
        assert dp["start_step"] == 9 and dp["stop_step"] == 11
        assert (dp["gen"], dp["rank"]) == (1, 0)   # stamped like every event
        # the acceptance self-consistency: the four phases sum to the
        # captured device window
        total = (dp["compute_ms"] + dp["comm_hidden_ms"]
                 + dp["comm_exposed_ms"] + dp["host_gap_ms"])
        assert dp["window_ms"] > 0
        assert total == pytest.approx(dp["window_ms"], rel=1e-3)
        assert tele_device.covers_step(dp, 8)      # trigger association
        assert tele_device.covers_step(dp, 9)      # window containment
        assert not tele_device.covers_step(dp, 20)

        # `telemetry summary` renders the device split beside the wall
        # split — text and --json both
        assert telemetry_main(["summary", str(p1)]) == 0
        out = capsys.readouterr().out
        assert "device-time split" in out and "profiled window(s)" in out
        assert "exposed-comm ratio" in out
        assert telemetry_main(["summary", str(p1), "--json"]) == 0
        s = json.loads(capsys.readouterr().out)
        assert s["device"]["profiles"] == len(profiles)
        assert set(s["device"]["split_ms"]) == set(
            tele_device.DEVICE_PHASES)
        assert any(w.get("trigger_step") == 8
                   for w in s["device"]["windows"])

        # the aggregator's straggler row gains the device block
        agg = aggregate_streams([p0, p1])
        hits = [s for s in agg["stragglers"]
                if s["phase"] == "data_wait" and s["gen"] == 1
                and s["step"] == 8]
        assert hits, agg["stragglers"]
        assert "device" in hits[0]
        assert hits[0]["device"]["reason"] == "anomaly:loader_stall"
        assert hits[0]["device"]["trigger_step"] == 8
        # per-stream device split rides the fleet summary too
        stalled_stream = [st for st in agg["streams"] if st["gen"] == 1][0]
        assert stalled_stream["device"]["profiles"] == len(profiles)
        assert [st for st in agg["streams"]
                if st["gen"] == 0][0]["device"] is None

        # ... and the stitched trace draws the captured window on tid 2
        from distributed_pytorch_training_tpu.telemetry.aggregate import (
            split_streams, stitch_perfetto,
        )
        trace = stitch_perfetto(split_streams([p0, p1]))
        dev = [e for e in trace["traceEvents"]
               if e.get("name") == "device_profile" and e["ph"] == "X"]
        assert len(dev) == len(profiles)
        assert all(e["tid"] == 2 for e in dev)
        assert any(e["dur"] == pytest.approx(dp["window_ms"] * 1e3)
                   for e in dev)


class TestGlobalStepLabels:
    def test_step_hook_receives_global_labels_on_resume(self, mesh8):
        """The loop hands step_hook the SAME global label the spans and
        the watchdog use (start_step + i) — on a mid-epoch resume an
        armed window's step range must line up against the straggler
        table's flagged steps, not restart at 0."""
        from distributed_pytorch_training_tpu.resilience.__main__ import (
            _build_rig,
        )

        trainer, _, loader = _build_rig(mesh8, seed=0, dataset_size=160,
                                        per_device_batch=2)
        metrics = {"loss_sum": jnp.float32(1.0),
                   "correct": jnp.float32(1.0),
                   "weight": jnp.float32(16.0)}
        trainer._train_step = lambda s, b, k: (s, metrics)
        seen = []
        spe = len(loader)
        trainer.train_epoch(None, loader.epoch(0, start_step=4), 0, spe,
                            start_step=4, step_hook=seen.append)
        assert seen == list(range(4, spe))


# ---------------------------------------------------------------------------
# straggler device attribution on synthetic streams (fleet-median factor)
# ---------------------------------------------------------------------------


def _write_synthetic_stream(path, gen, *, stall_at=None, profile=None):
    """Minimal two-phase stream; ``profile`` injects a device_profile."""
    with open(path, "w", encoding="utf-8") as f:
        def emit(kind, name, **fields):
            f.write(json.dumps({"v": 2, "ts": 1000.0, "kind": kind,
                                "name": name, "gen": gen, "rank": 0,
                                **fields}) + "\n")

        emit("meta", "stream", schema=2, run_id=f"g{gen}", pid=100 + gen)
        for step in range(10):
            wait = 1.5 if step == stall_at else 0.004
            emit("span", "data_wait", dur_ms=wait * 1e3, step=step)
            emit("span", "step_dispatch", dur_ms=4.0, step=step)
        if profile is not None:
            emit("device_profile", "device_profile", **profile)
        emit("counter", "epoch_time_s", value=2.0, epoch=0)
    return path


class TestStragglerDeviceAttribution:
    def test_overlapping_profile_attributes_with_fleet_factor(self,
                                                              tmp_path):
        from distributed_pytorch_training_tpu.telemetry.aggregate import (
            aggregate_streams,
        )

        slow = {"start_step": 4, "stop_step": 6, "steps": 2,
                "reason": "anomaly:loader_stall", "trigger_step": 5,
                "window_ms": 100.0, "compute_ms": 20.0,
                "comm_hidden_ms": 5.0, "comm_exposed_ms": 41.0,
                "host_gap_ms": 34.0, "exposed_comm_ratio": 0.89,
                "by_op_ms": {"all-reduce": 46.0}}
        clean = {"start_step": 4, "stop_step": 6, "steps": 2,
                 "reason": "http", "trigger_step": None,
                 "window_ms": 100.0, "compute_ms": 85.0,
                 "comm_hidden_ms": 5.0, "comm_exposed_ms": 10.0,
                 "host_gap_ms": 0.0, "exposed_comm_ratio": 0.66,
                 "by_op_ms": {"all-reduce": 15.0}}
        p0 = _write_synthetic_stream(tmp_path / "r0.jsonl", 0,
                                     profile=clean)
        p1 = _write_synthetic_stream(tmp_path / "r1.jsonl", 1,
                                     stall_at=5, profile=slow)
        agg = aggregate_streams([p0, p1])
        hit = [s for s in agg["stragglers"] if s["gen"] == 1][0]
        d = hit["device"]
        assert d["dominant_op"] == "all-reduce"
        assert d["split_ms"]["comm_exposed"] == 41.0
        # 41 / clean's 10 exposed ms — the "4.1x fleet median" headline
        assert d["exposed_vs_fleet_median"] == 4.1

    def test_no_overlap_keeps_span_fallback(self, tmp_path):
        from distributed_pytorch_training_tpu.telemetry.aggregate import (
            aggregate_streams,
        )

        far = {"start_step": 0, "stop_step": 2, "steps": 2,
               "reason": "http", "trigger_step": None,
               "window_ms": 10.0, "compute_ms": 10.0,
               "comm_hidden_ms": 0.0, "comm_exposed_ms": 0.0,
               "host_gap_ms": 0.0}
        p0 = _write_synthetic_stream(tmp_path / "r0.jsonl", 0)
        p1 = _write_synthetic_stream(tmp_path / "r1.jsonl", 1,
                                     stall_at=5, profile=far)
        agg = aggregate_streams([p0, p1])
        hit = [s for s in agg["stragglers"] if s["gen"] == 1][0]
        assert "device" not in hit   # span-based attribution stands


# ---------------------------------------------------------------------------
# federation: ONE /metrics page over the per-rank ports
# ---------------------------------------------------------------------------


class TestFederation:
    def test_merged_page_is_gen_rank_labelled(self, tmp_path):
        rec_a = telemetry.Recorder(str(tmp_path / "a.jsonl"), gen=0,
                                   rank=0)
        rec_b = telemetry.Recorder(str(tmp_path / "b.jsonl"), gen=1,
                                   rank=0)
        a = telemetry.MetricsServer(0, recorder=rec_a, backend="cpu")
        b = telemetry.MetricsServer(0, recorder=rec_b, backend="cpu")
        pa, pb = a.start(), b.start()
        fed = telemetry.FederationServer(0, targets=[pa, pb])
        fport = fed.start()
        try:
            rec_a.span_event("step_dispatch", 0.004, step=3)
            rec_b.span_event("step_dispatch", 0.004, step=7)
            rec_b.gauge("world_size", 4)
            _, body = _scrape(fport)
            assert "dpt_federation_targets 2" in body
            assert 'dpt_federation_up{gen="0",rank="0"} 1' in body
            assert 'dpt_federation_up{gen="1",rank="0"} 1' in body
            assert 'dpt_steps_total{gen="0",rank="0"} 1' in body
            assert 'dpt_steps_total{gen="1",rank="0"} 1' in body
            assert 'dpt_last_step{gen="1",rank="0"} 7' in body
            assert ('dpt_gauge{gen="1",rank="0",name="world_size"} 4'
                    in body)
            # labelled lines (build_info) pass through un-doubled
            assert body.count('dpt_build_info{gen="0"') == 1
            # one TYPE line per metric family, not per target
            assert body.count("# TYPE dpt_steps_total counter") == 1
            # /healthz names every target
            status, hz = _scrape(fport, "/healthz")
            detail = json.loads(hz)
            assert detail["healthy"] is True
            assert set(detail["targets"]) == {"gen0/rank0", "gen1/rank0"}
        finally:
            fed.stop()
            a.stop()
            b.stop()

    def test_exited_target_stays_cached_marked_down(self, tmp_path):
        rec = telemetry.Recorder(str(tmp_path / "a.jsonl"), gen=2, rank=0)
        server = telemetry.MetricsServer(0, recorder=rec)
        port = server.start()
        fed = telemetry.FederationServer(0, targets=[port])
        fport = fed.start()
        try:
            rec.span_event("step_dispatch", 0.004, step=5)
            _, body = _scrape(fport)
            assert 'dpt_federation_up{gen="2",rank="0"} 1' in body
            server.stop()   # the child "exited"
            _, body = _scrape(fport)
            # last page kept in the merge, marked down — the fleet's
            # final federated page carries every generation
            assert 'dpt_federation_up{gen="2",rank="0"} 0' in body
            assert 'dpt_steps_total{gen="2",rank="0"} 1' in body
            with pytest.raises(urllib.error.HTTPError) as err:
                _scrape(fport, "/healthz")
            assert err.value.code == 503
        finally:
            fed.stop()
            server.stop()

    def test_no_targets_page_is_empty_but_serves(self):
        fed = telemetry.FederationServer(0, targets=[])
        fport = fed.start()
        try:
            _, body = _scrape(fport)
            assert "dpt_federation_targets 0" in body
        finally:
            fed.stop()
