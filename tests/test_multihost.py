"""Two-process multi-host runtime test (VERDICT r2 #9: the rendezvous
branches, host collectives, and multi-process shard_batch had no live test).

Spawns 2 real OS processes on the CPU backend, 2 virtual devices each — the
smallest honest model of a 2-host pod. They rendezvous through
``jax.distributed.initialize`` via the ``DPT_*`` env contract
(runtime/dist.py), mirroring the reference's torchrun ``env://`` rendezvous
(/root/reference/train_ddp.py:53-68). The worker (tests/_multihost_worker.py)
asserts the whole surface: DistContext topology, barrier,
broadcast_from_main, reduce_scalar, host_all_gather, per-process seed rule,
multi-host shard_batch, and a 4-step sharded training run whose loss
decreases and agrees bit-for-bit across processes.
"""

import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

WORKER = Path(__file__).parent / "_multihost_worker.py"
REPO = Path(__file__).parent.parent


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.xfail(
    strict=False,
    reason="container jax 0.4.37's XLA:CPU backend cannot run MULTIPROCESS "
           "computations: the workers rendezvous fine, but the first host "
           "collective (barrier -> multihost_utils.sync_global_devices -> "
           "jit psum over both processes) fails with INVALID_ARGUMENT: "
           "'Multiprocess computations aren't implemented on the CPU "
           "backend'. Newer jaxlib CPU builds (cross-host collectives via "
           "gloo/mpi) pass this test unchanged, so it stays xfail — not "
           "skip — to light up green the moment the runtime supports it.")
def test_two_process_rendezvous_and_training():
    """Root cause of the long-standing tier-1 failure (triaged, ISSUE 8):
    NOT a rendezvous bug in runtime/dist.py — `jax.distributed.initialize`
    succeeds and both workers see the 2-process topology — but a jaxlib
    capability gap: this container's XLA:CPU client has no cross-process
    collective implementation, so every multi-process computation on it is
    rejected at dispatch. The single-process multi-device suite (conftest's
    8-device virtual mesh) is unaffected: its collectives never leave the
    process."""
    # bounded by the workers' communicate(timeout=240) below
    port = _free_port()
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
            "DPT_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
            "DPT_NUM_PROCESSES": "2",
            "DPT_PROCESS_ID": str(rank),
        })
        # a worker must not inherit the parent test's single-process state
        env.pop("JAX_NUM_CPU_DEVICES", None)
        procs.append(subprocess.Popen(
            [sys.executable, str(WORKER)], env=env, cwd=str(REPO),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))

    outs = []
    try:
        for rank, p in enumerate(procs):
            out, err = p.communicate(timeout=240)
            outs.append((rank, p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    for rank, rc, out, err in outs:
        assert rc == 0, (
            f"worker {rank} failed rc={rc}\nstdout:\n{out}\nstderr:\n{err}")
        assert f"WORKER_OK rank={rank}" in out, out

    # both ranks converged to the same loss (printed value matches)
    import re
    losses = {re.search(r"loss=([0-9.]+)", out).group(1)
              for _, _, out, _ in outs}
    assert len(losses) == 1, f"ranks diverged: {losses}"
