"""Ulysses (all-to-all head-sharded) attention vs the XLA reference path.

Sequence sharded over `seq`, two all-to-alls per call; output must equal
full attention exactly (same math, no online-softmax approximation).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_training_tpu.models.layers import (
    causal_mask,
    dot_product_attention,
)
from distributed_pytorch_training_tpu.ops import ulysses_attention
from distributed_pytorch_training_tpu.parallel import MeshSpec, build_mesh


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.RandomState(0)
    shape = (2, 16, 4, 8)  # (B, S, H, D)
    return tuple(jnp.asarray(rng.randn(*shape), jnp.float32) for _ in range(3))


@pytest.mark.parametrize("causal", [False, True])
def test_matches_reference(devices, qkv, causal):
    q, k, v = qkv
    mesh = build_mesh(MeshSpec(data=2, seq=4), devices=devices)
    mask = causal_mask(q.shape[1]) if causal else None
    want = dot_product_attention(q, k, v, mask=mask)
    got = ulysses_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_grads_match_reference(devices, qkv):
    q, k, v = qkv
    mesh = build_mesh(MeshSpec(data=2, seq=4), devices=devices)

    def loss_ref(q, k, v):
        return (dot_product_attention(
            q, k, v, mask=causal_mask(q.shape[1])) ** 2).sum()

    def loss_uly(q, k, v):
        return (ulysses_attention(q, k, v, mesh, causal=True) ** 2).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_uly = jax.grad(loss_uly, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_uly, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_rejects_indivisible_heads(devices, qkv):
    q, k, v = qkv  # H=4
    mesh = build_mesh(MeshSpec(seq=8), devices=devices)
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention(q, k, v, mesh)


def test_seq1_degenerates_to_reference(devices, qkv):
    q, k, v = qkv
    mesh = build_mesh(MeshSpec(data=8), devices=devices)
    want = dot_product_attention(q, k, v)
    got = ulysses_attention(q, k, v, mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
