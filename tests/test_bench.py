"""bench.py contract tests — the driver consumes EXACTLY ONE JSON line from
stdout; a hung or crashed backend must degrade to an error-JSON, never to
silence (the round-1 bench lost its round to an unguarded backend hang)."""

import json

import pytest
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def _run_bench(extra_args, env_extra=None, timeout=120):
    env = dict(os.environ)
    # the subprocess must not inherit the axon TPU platform: the contract
    # under test is bench's own plumbing, not the accelerator
    env["JAX_PLATFORMS"] = "cpu"
    env.update(env_extra or {})
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py")] + extra_args,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        timeout=timeout, env=env, cwd=str(REPO))
    json_lines = [l for l in proc.stdout.decode().splitlines()
                  if l.startswith("{")]
    return proc, json_lines


@pytest.mark.slow
def test_watchdog_emits_error_json_when_backend_hangs():
    """A backend that blocks forever in init (observed live: a wedged
    tunnel made jax.devices() hang indefinitely) must not eat the round:
    the watchdog stops the inner process at --deadline and the parent
    prints the error-JSON line the driver requires."""
    proc, lines = _run_bench(
        ["--deadline", "5", "--quick"],
        env_extra={"DPT_BENCH_TEST_HANG": "1"}, timeout=90)
    assert proc.returncode != 0
    assert len(lines) == 1, proc.stdout
    result = json.loads(lines[0])
    assert result["value"] == 0.0
    assert "deadline" in result["error"]
    assert result["unit"] == "samples/sec/chip"
    assert set(result) >= {"metric", "value", "unit", "vs_baseline"}


@pytest.mark.slow
def test_watchdog_salvages_flushed_result_json_on_deadline():
    """A result that was already measured and flushed must survive a
    deadline hit (e.g. the inner hangs in PJRT client teardown, or an
    extra config overruns the soft-deadline margin): the parent drains
    the pipe and reports the last JSON line with rc=0."""
    # deadline 15 not 5: the inner needs interpreter startup time to reach
    # the flush under load, and the test's point is the salvage, not speed
    import bench
    before = bench.HISTORY_PATH.read_text() \
        if bench.HISTORY_PATH.exists() else ""
    proc, lines = _run_bench(
        ["--deadline", "15", "--quick"],
        env_extra={"DPT_BENCH_TEST_HANG": "after-json"}, timeout=120)
    assert proc.returncode == 0
    assert len(lines) == 1, proc.stdout
    result = json.loads(lines[0])
    assert result["value"] == 42.0
    assert "error" not in result
    # The parent's salvage-append runs in a subprocess, beyond monkeypatch
    # reach: the committed provenance log must not gain the 42.0 test row
    # (it did once — a junk row had to be stripped from bench_history.jsonl).
    after = bench.HISTORY_PATH.read_text() \
        if bench.HISTORY_PATH.exists() else ""
    assert after == before


@pytest.mark.slow
def test_wedged_probes_fail_inside_init_budget_not_at_deadline():
    """Round 3's actual failure: each in-process jax.devices() attempt
    blocked ~25 minutes, so five retries outlived the driver (rc=124).
    With subprocess probes, a wedged backend must burn only --init-budget
    seconds and then emit the error-JSON — long before --deadline."""
    import time

    t0 = time.monotonic()
    proc, lines = _run_bench(
        ["--deadline", "120", "--init-budget", "6", "--probe-timeout", "2",
         "--quick"],
        env_extra={"DPT_BENCH_TEST_WEDGE": "1"}, timeout=110)
    elapsed = time.monotonic() - t0
    assert proc.returncode != 0
    assert len(lines) == 1, proc.stdout
    result = json.loads(lines[0])
    assert result["value"] == 0.0
    assert "budget" in result["error"], result
    # the whole point: error lands well before the 120s deadline
    assert elapsed < 90, f"error-JSON took {elapsed:.0f}s (deadline-bound?)"


def test_default_deadline_fits_inside_driver_budget():
    """r3's --deadline 2400 outlived the driver's own timeout, so the
    watchdog never fired and the round recorded rc=124 with no JSON.
    Pin the default inside the budget the verdict sized (<=900s)."""
    sys.path.insert(0, str(REPO))
    import bench

    args = bench._parse([])
    assert args.deadline <= 900
    assert args.init_budget <= 360
    assert args.probe_timeout <= args.init_budget


def test_history_append_writes_jsonl(tmp_path, monkeypatch):
    """Every completed bench appends its full result dict (provenance for
    the README table) to experiments/results/bench_history.jsonl."""
    sys.path.insert(0, str(REPO))
    import bench

    monkeypatch.setattr(bench, "HISTORY_PATH", tmp_path / "hist.jsonl")
    bench._record_history({"metric": "m", "value": 1.0, "configs": []})
    bench._record_history({"metric": "m", "value": 2.0, "configs": []})
    rows = [json.loads(l) for l in
            (tmp_path / "hist.jsonl").read_text().splitlines()]
    assert [r["value"] for r in rows] == [1.0, 2.0]
    assert all("timestamp" in r for r in rows)


def test_tunnel_status_classifies_relay_liveness(monkeypatch):
    """The recurring "wedged backend" of rounds 3-5 was finally attributed
    live to the tunnel relay process dying mid-compile (CHIP_STATUS.md
    2026-07-31: remote_compile connection refused after a 40-minute
    UNAVAILABLE retry loop). The diagnostic must classify a listening vs
    dead relay and never crash on a malformed port list."""
    import socket
    sys.path.insert(0, str(REPO))
    import bench

    # no usable ports configured -> no claim either way
    monkeypatch.setenv("DPT_RELAY_PORTS", " ,")
    assert bench._tunnel_status() is None

    # a live listener on an explicitly configured port -> tunnel up
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]
    try:
        monkeypatch.setenv("DPT_RELAY_PORTS", str(port))
        assert "tunnel up" in bench._tunnel_status()
        # one listening + one closed -> partial (remote compile will fail)
        closed = socket.socket()
        closed.bind(("127.0.0.1", 0))  # bound but NOT listening
        try:
            monkeypatch.setenv("DPT_RELAY_PORTS",
                               f"{port},{closed.getsockname()[1]}")
            assert "PARTIALLY down" in bench._tunnel_status()
        finally:
            closed.close()
    finally:
        srv.close()

    # all configured ports closed -> the no-client-side-remedy message
    # (bound-but-not-listening holds the port so nothing can race onto it)
    down = socket.socket()
    down.bind(("127.0.0.1", 0))
    try:
        monkeypatch.setenv("DPT_RELAY_PORTS", str(down.getsockname()[1]))
        assert "DOWN" in bench._tunnel_status()
    finally:
        down.close()


def test_empirical_wall_gate_uses_history_only_when_cache_primed(
        tmp_path, monkeypatch):
    """The static per-config cost estimates are sized for COLD compiles; a
    primed compile cache plus a committed measured wall time for the same
    label on the same chip must shrink the reservation (never grow it), so
    the default-deadline driver run can fit the full matrix."""
    sys.path.insert(0, str(REPO))
    import bench

    hist = tmp_path / "hist.jsonl"
    hist.write_text(json.dumps({
        "chip": "TPU v5 lite",
        "configs": [{"label": "gpt2_124m", "wall_s": 80.0},
                    {"label": "resnet50", "wall_s": 600.0},
                    {"model": "resnet18", "bf16": True,
                     "per_device_batch": 4096, "wall_s": 226.0}],
    }) + "\n" + json.dumps({
        "chip": "cpu",  # other-chip rows must not leak into the gate
        "configs": [{"label": "bert_base", "wall_s": 1.0}],
    }) + "\n")
    monkeypatch.setattr(bench, "HISTORY_PATH", hist)

    walls = bench._measured_walls("TPU v5 lite")
    assert walls == {"gpt2_124m": 80.0, "resnet50": 600.0}

    # the headline (label-less resnet18 bf16 row) is the warmth reference
    assert bench._headline_wall("TPU v5 lite", 4096) == 226.0
    assert bench._headline_wall("TPU v5 lite", 128) is None

    # the reference is the MAX committed wall (a newer warm rerun must not
    # lower it into unprovability), capped at 400s against outliers
    extra = [json.dumps({"chip": "TPU v5 lite", "configs": [
        {"model": "resnet18", "bf16": True,
         "per_device_batch": 4096, "wall_s": w}]}) for w in (61.0, 999.0)]
    hist.write_text(hist.read_text() + "\n".join(extra) + "\n")
    assert bench._headline_wall("TPU v5 lite", 4096) == 400.0

    # a truncated line mid-log must not drop the rows after it
    hist.write_text(hist.read_text() + '{"chip": "TPU v5 l\n' + json.dumps(
        {"chip": "TPU v5 lite",
         "configs": [{"label": "bert_base", "wall_s": 70.0}]}) + "\n")
    assert bench._measured_walls("TPU v5 lite")["bert_base"] == 70.0

    # primed + measured -> 1.5x + 60, capped by the static estimate
    assert bench._est_for("gpt2_124m", 400, walls, True) == 180.0
    assert bench._est_for("resnet50", 420, walls, True) == 420  # cap holds
    # unprimed cache or unmeasured label -> static estimate untouched
    assert bench._est_for("gpt2_124m", 400, walls, False) == 400
    assert bench._est_for("bert_base", 400, walls, True) == 400

    # code-fingerprint filter: warm walls must come from rows recorded by
    # the RUNNING code state — a model edit or EXTRA_CONFIGS kwargs bump
    # changes the fingerprint and silently reverts to cold static gates
    fp = bench._code_fingerprint()
    assert bench._measured_walls("TPU v5 lite", fingerprint=fp) == {}
    hist.write_text(hist.read_text() + json.dumps(
        {"chip": "TPU v5 lite", "code_fingerprint": fp,
         "configs": [{"label": "vit_b16", "wall_s": 90.0},
                     {"model": "resnet18", "bf16": True,
                      "per_device_batch": 4096, "wall_s": 200.0}]}) + "\n")
    assert bench._measured_walls("TPU v5 lite", fingerprint=fp) == \
        {"vit_b16": 90.0}
    # the headline cold-reference stays CROSS-fingerprint (a generation
    # whose first headline ran warm would otherwise never prove warmth):
    # max(226, 61, 999-capped-400, 200) -> 400
    assert bench._headline_wall("TPU v5 lite", 4096) == 400.0
    # ...and history appends stamp the fingerprint automatically
    monkeypatch.setattr(bench, "HISTORY_PATH", tmp_path / "h2.jsonl")
    bench._record_history({"metric": "m", "value": 1.0, "configs": []})
    row = json.loads((tmp_path / "h2.jsonl").read_text())
    assert row["code_fingerprint"] == fp


def test_extra_config_bf16_override_and_fp32_arm_identity():
    """EXTRA_CONFIGS entries default to bf16 but may override it (fp32
    arms); the salvage marker-resolution must key the HEADLINE fp32 arm on
    the label-less bf16=False config, so a labeled fp32 extra cannot mask
    a missing headline arm."""
    sys.path.insert(0, str(REPO))
    import bench

    merged = {label: {"bf16": True, **kw}
              for label, _, _, kw in bench.EXTRA_CONFIGS}
    assert merged["gpt2_124m_fp32"]["bf16"] is False
    assert all(v["bf16"] for k, v in merged.items() if not k.endswith("_fp32"))

    # salvage resolution: gpt2 fp32 extra present, headline fp32 absent
    d = {"configs": [{"model": "resnet18", "bf16": True},
                     {"model": "gpt2_124m", "bf16": False,
                      "label": "gpt2_124m_fp32"}],
         "configs_skipped": ["<provisional>"]}
    bench._resolve_provisional_marker(d, None)
    assert "fp32" in d["configs_skipped"]


def test_chunked_salvage_resolves_unmeasured_labels():
    """A chunked --only run SIGTERMed mid-chunk flushes provisional lines
    carrying the "<provisional>" marker; the salvage path must resolve it to
    the selected-but-never-measured labels (the r5 resnet50+vit_b16 chunk
    committed `configs_skipped: []` with vit_b16 missing before this)."""
    sys.path.insert(0, str(REPO))
    import bench

    d = {"configs": [{"model": "resnet50", "bf16": True,
                      "label": "resnet50"}],
         "configs_skipped": ["<provisional>"]}
    bench._resolve_provisional_marker(d, "resnet50,vit_b16")
    assert d["configs_skipped"] == ["vit_b16"]


def test_finalize_salvaged_records_and_resolves(tmp_path, monkeypatch):
    """The parent's salvage treatment applies to EVERY un-finalized measured
    line — deadline SIGTERMs and inner crashes alike: the marker resolves,
    the row lands in history, and the returned stdout line AGREES with the
    committed row (a raw passthrough once printed a literal "<provisional>"
    to the driver while history said ["vit_b16"])."""
    sys.path.insert(0, str(REPO))
    import bench

    hist = tmp_path / "h.jsonl"
    monkeypatch.setattr(bench, "HISTORY_PATH", hist)
    monkeypatch.delenv("DPT_BENCH_TEST_HANG", raising=False)
    monkeypatch.delenv("DPT_BENCH_TEST_WEDGE", raising=False)
    line = json.dumps({
        "metric": "resnet50_train_throughput_bf16", "value": 2708.1,
        "unit": "samples/sec/chip", "vs_baseline": None,
        "configs": [{"model": "resnet50", "bf16": True, "label": "resnet50"}],
        "configs_skipped": ["<provisional>"]})

    out = bench._finalize_salvaged(line, "inner rc=-9", "resnet50,vit_b16")
    printed = json.loads(out)
    assert printed["configs_skipped"] == ["vit_b16"]
    assert printed["salvaged"] == "inner rc=-9"
    row = json.loads(hist.read_text())
    assert {k: v for k, v in row.items()
            if k not in ("timestamp", "code_fingerprint")} == printed

    # idempotent: the same line again (teardown-hang after the inner DID
    # record) must not append a duplicate row and passes through untouched
    out2 = bench._finalize_salvaged(out, "deadline SIGTERM", "resnet50")
    assert out2 == out
    assert len(hist.read_text().splitlines()) == 1

    # an error line is never recorded
    err_line = json.dumps({"metric": "m", "value": 0.0, "error": "boom"})
    assert bench._finalize_salvaged(err_line, "x", None) == err_line
    assert len(hist.read_text().splitlines()) == 1


def test_relay_deathwatch_aborts_inner_when_tunnel_dies(tmp_path):
    """A relay that dies mid-run must abort the inner within ~2 sample
    intervals (rc=70) instead of hanging in UNAVAILABLE retries until the
    watchdog SIGTERM (observed live: 24+ min of blocked compile,
    CHIP_STATUS.md 12:09). PARTIAL death counts: losing just the compile
    port hangs compiles the same way (03:19: /remote_compile refused, 40
    min retry loop), so only ONE of the two armed ports dies here. The
    parent's crash-salvage branch then keeps any flushed measurement."""
    import socket
    import time

    def listener():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        s.listen(8)
        return s

    srv_dies, srv_stays = listener(), listener()
    ports = f"{srv_dies.getsockname()[1]},{srv_stays.getsockname()[1]}"

    def accept_forever(s):
        # a real relay accepts; without this the watch's liveness probes
        # fill the backlog and the port would read as down too early.
        # Timeout-polling accept (not a blocking accept): a thread blocked
        # in kernel accept() pins the socket open past close(), so the
        # deliberate close would not actually stop the port listening.
        s.settimeout(0.2)
        while True:
            try:
                conn, _ = s.accept()
                conn.close()
            except socket.timeout:
                continue
            except OSError:
                return

    import threading
    # BOTH listeners run accept loops: srv_dies must read as alive right up
    # to its deliberate close, or the watch's own probes fill its backlog(8)
    # and trip the deathwatch before the alive-then-dies transition the test
    # exists to exercise (ADVICE r5 #4). The loop thread ends when close()
    # invalidates the fd (accept raises OSError).
    threading.Thread(target=accept_forever, args=(srv_dies,),
                     daemon=True).start()
    threading.Thread(target=accept_forever, args=(srv_stays,),
                     daemon=True).start()
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu",
                "DPT_RELAY_PORTS": ports,
                "DPT_RELAY_WATCH_INTERVAL": "0.3",
                "DPT_BENCH_TEST_HANG": "1"})
    errf = tmp_path / "deathwatch_stderr.log"
    with open(errf, "wb") as errh:
        proc = subprocess.Popen(
            [sys.executable, str(REPO / "bench.py"), "--_inner",
             "--deadline", "120"],
            stdout=subprocess.PIPE, stderr=errh, env=env, cwd=str(REPO))
    try:
        # wait for the ARMED log line — closing the listener before the
        # inner's arm-time check correctly DISARMS the watch (not a
        # tunneled environment), which is not the scenario under test
        deadline = time.time() + 60
        while b"deathwatch armed" not in errf.read_bytes():
            assert time.time() < deadline, errf.read_bytes()[-500:]
            assert proc.poll() is None, errf.read_bytes()[-500:]
            time.sleep(0.2)
        srv_dies.close()  # the compile port "dies"; the other stays up
        proc.wait(timeout=30)
        assert proc.returncode == 70, (proc.returncode,
                                       errf.read_bytes()[-500:])
        assert b"relay tunnel DIED" in errf.read_bytes()
    finally:
        if proc.poll() is None:
            proc.kill()
        srv_dies.close()
        srv_stays.close()
