"""bench.py contract tests — the driver consumes EXACTLY ONE JSON line from
stdout; a hung or crashed backend must degrade to an error-JSON, never to
silence (the round-1 bench lost its round to an unguarded backend hang)."""

import json
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def _run_bench(extra_args, env_extra=None, timeout=120):
    env = dict(os.environ)
    # the subprocess must not inherit the axon TPU platform: the contract
    # under test is bench's own plumbing, not the accelerator
    env["JAX_PLATFORMS"] = "cpu"
    env.update(env_extra or {})
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py")] + extra_args,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        timeout=timeout, env=env, cwd=str(REPO))
    json_lines = [l for l in proc.stdout.decode().splitlines()
                  if l.startswith("{")]
    return proc, json_lines


def test_watchdog_emits_error_json_when_backend_hangs():
    """A backend that blocks forever in init (observed live: a wedged
    tunnel made jax.devices() hang indefinitely) must not eat the round:
    the watchdog kills the inner process at --deadline and the parent
    prints the error-JSON line the driver requires."""
    proc, lines = _run_bench(
        ["--deadline", "5", "--quick"],
        env_extra={"DPT_BENCH_TEST_HANG": "1"}, timeout=90)
    assert proc.returncode != 0
    assert len(lines) == 1, proc.stdout
    result = json.loads(lines[0])
    assert result["value"] == 0.0
    assert "deadline" in result["error"]
    assert result["unit"] == "samples/sec/chip"
    assert set(result) >= {"metric", "value", "unit", "vs_baseline"}
