"""control/ (ISSUE 20): the self-driving fleet's policy layer.

Unit legs pin each loop in isolation — the straggler persistence policy
(N consecutive flagged steps, history dropped across resizes), the
capacity probes and their CONTAINMENT inside CapacityWatch (a raising or
hanging feed degrades to the last committed reading, never the poll/grow
path), the contract gate (a failing candidate is refused with findings,
never applied), and `apply_decision` as the one entry to the re-plan
surface. Live legs drive the real Supervisor: a gated `boundary_retune`
at a segment boundary (applied AND refused twins — the refused run must
continue on the old config), the autopilot-off pin (control=None leaves
the stream byte-free of control events), and the acceptance e2e —
`resilience chaos --autopilot` proving detect -> evict -> grow with
bitwise post-resize parity, then feeding the SAME stream back through
/metrics and `telemetry summary` so every renderer of the decision
record is pinned against the artifact the run actually wrote.
"""

import json
import threading
import time
from pathlib import Path

import pytest

from distributed_pytorch_training_tpu import telemetry
from distributed_pytorch_training_tpu.control import (
    Autopilot, CONTROL_DECISION_KIND, ControlDecision, DECISION_ACTIONS,
    FileCapacityFeed, PerfTuner, StragglerEvictionPolicy, TUNABLE_KEYS,
    apply_decision, contract_gate, emit_decision, heartbeat_capacity_probe,
)
from distributed_pytorch_training_tpu.control.tuner import DEFAULT_CANDIDATE
from distributed_pytorch_training_tpu.resilience.capacity import CapacityWatch
from distributed_pytorch_training_tpu.telemetry.aggregate import (
    StreamSegment, detect_stragglers,
)
from distributed_pytorch_training_tpu.telemetry.device import (
    DEVICE_PROFILE_KIND,
)


@pytest.fixture
def stream(tmp_path):
    """A configured telemetry recorder writing to a tmp JSONL."""
    path = tmp_path / "stream.jsonl"
    telemetry.configure(str(path))
    yield path
    telemetry.reset()


def _tail(n=200):
    rec = telemetry.get()
    return rec.tail(n) if rec is not None else []


def _probe_threads():
    return sum(1 for t in threading.enumerate()
               if t.name == "dpt-capacity-probe")


# ---------------------------------------------------------------------------
# the decision record
# ---------------------------------------------------------------------------


class TestControlDecision:
    def test_unknown_action_rejected(self):
        with pytest.raises(ValueError, match="unknown control action"):
            ControlDecision(action="reboot", reason="nope")

    def test_fields_casts_and_skips_none(self):
        d = ControlDecision(action="evict", reason="slow", rank=3,
                            world_from=8.0, world_to=4,
                            evidence={"steps": [5, 6, 7]})
        f = d.fields()
        assert f["action"] == "evict" and f["applied"] is False
        assert f["rank"] == 3 and isinstance(f["world_from"], int)
        assert f["evidence"] == {"steps": [5, 6, 7]}
        assert "epoch" not in f and "step" not in f  # None fields dropped

    def test_emit_unconfigured_is_a_noop(self):
        telemetry.reset()
        d = ControlDecision(action="detect", reason="r")
        assert emit_decision(d) is d  # no raise, chains the decision

    def test_emit_lands_on_the_stream(self, stream):
        emit_decision(ControlDecision(action="grow", reason="back",
                                      world_from=4, world_to=8,
                                      applied=True))
        evs = [e for e in _tail() if e.get("kind") == CONTROL_DECISION_KIND]
        assert len(evs) == 1
        ev = evs[0]
        # the event carries BOTH name (what the renderers key on) and the
        # action field (the chaos CLI's rename target)
        assert ev["name"] == "grow" and ev["action"] == "grow"
        assert ev["applied"] is True and ev["world_to"] == 8
        assert set(DECISION_ACTIONS) >= {"detect", "evict", "grow",
                                         "retune", "refuse"}


# ---------------------------------------------------------------------------
# loop (1): the persistence policy
# ---------------------------------------------------------------------------


def _row(step, rank=1, gen=0, dur=1.0, factor=10.0, phase="data_wait"):
    return {"gen": gen, "rank": rank, "step": step, "phase": phase,
            "dur_s": dur, "baseline_s": dur / factor, "factor": factor,
            "basis": "peers_at_step", "peers": 7}


class TestStragglerPolicy:
    def test_n_minus_one_flags_do_not_convict(self):
        """The ISSUE 20 edge satellite: N-1 consecutive flags must NOT
        trigger eviction; the Nth does."""
        pol = StragglerEvictionPolicy(n_consecutive=3)
        pol.observe_rows([_row(5), _row(6)])
        assert pol.verdict() is None
        pol.observe_rows([_row(7)])
        v = pol.verdict()
        assert v is not None and v["rank"] == 1 and v["steps"] == [5, 6, 7]

    def test_non_consecutive_flags_do_not_convict(self):
        pol = StragglerEvictionPolicy(n_consecutive=3)
        pol.observe_rows([_row(5), _row(7), _row(9)])
        assert pol.verdict() is None

    def test_resize_drops_history(self):
        """Rank labels remap across ANY resize: two pre-resize flags on
        rank 1 plus one post-resize flag on 'rank 1' (a different host
        now) must not convict — the persistence-across-resize pin."""
        pol = StragglerEvictionPolicy(n_consecutive=3)
        pol.observe_rows([_row(5), _row(6)])
        pol.note_resize()
        pol.observe_rows([_row(7)])
        assert pol.verdict() is None
        assert pol.flagged_steps(0, 1) == [7]

    def test_observe_is_idempotent_keeping_worst(self):
        pol = StragglerEvictionPolicy(n_consecutive=1)
        pol.observe_rows([_row(5, dur=1.0)])
        pol.observe_rows([_row(5, dur=2.0), _row(5, dur=0.5)])
        assert pol.flagged_steps(0, 1) == [5]
        assert pol.verdict()["evidence"]["dur_s"] == 2.0

    def test_phases_outside_the_policy_are_ignored(self):
        pol = StragglerEvictionPolicy(n_consecutive=1)
        pol.observe_rows([_row(5, phase="eval_epoch")])
        assert pol.verdict() is None

    def test_worst_rank_wins(self):
        pol = StragglerEvictionPolicy(n_consecutive=3)
        pol.observe_rows([_row(s, rank=1) for s in (5, 6, 7)])
        pol.observe_rows([_row(s, rank=2) for s in (4, 5, 6, 7)])
        assert pol.verdict()["rank"] == 2  # longer run convicts first

    def test_bad_n_rejected(self):
        with pytest.raises(ValueError):
            StragglerEvictionPolicy(n_consecutive=0)


def _span_seg(rank, durs_ms, phase="data_wait", gen=0):
    """One synthetic stream segment: {step: dur_ms} spans of one phase."""
    events = [{"kind": "span", "name": phase, "step": s, "dur_ms": d}
              for s, d in sorted(durs_ms.items())]
    return StreamSegment(gen=gen, rank=rank, path=f"<r{rank}>",
                         anchor_ts=0.0, events=events)


class TestDetectorFeedsPolicy:
    def test_detector_rows_convict_the_stalled_rank(self):
        """The live wiring the autopilot rides: detect_stragglers over
        peer segments -> policy -> verdict names the persistent rank."""
        fast = _span_seg(0, {s: 1.0 for s in range(4, 8)})
        slow = _span_seg(1, {4: 1.0, 5: 900.0, 6: 900.0, 7: 900.0})
        rows = detect_stragglers([fast, slow])
        assert {r["step"] for r in rows} == {5, 6, 7}
        assert all(r["rank"] == 1 and r["basis"] == "peers_at_step"
                   for r in rows)
        pol = StragglerEvictionPolicy(n_consecutive=3)
        pol.observe_rows(rows)
        v = pol.verdict()
        assert v["rank"] == 1 and v["steps"] == [5, 6, 7]
        assert v["evidence"]["dur_s"] == 0.9

    def test_first_dispatch_exemption_holds_through_the_feed(self):
        """A relaunch's compile-dominated first step_dispatch must not
        feed the policy a phantom flag."""
        segs = [_span_seg(r, {0: 1.0, 1: 1.0}, phase="step_dispatch")
                for r in range(4)]
        segs.append(_span_seg(7, {0: 5000.0, 1: 1.0},
                              phase="step_dispatch"))
        rows = detect_stragglers(segs)
        assert rows == []


# ---------------------------------------------------------------------------
# loop (3): capacity probes + containment
# ---------------------------------------------------------------------------


class TestHeartbeatProbe:
    def test_proportional_to_up_ports(self):
        import socket

        live = socket.socket()
        live.bind(("127.0.0.1", 0))
        live.listen(1)
        live_port = live.getsockname()[1]
        dead = socket.socket()
        dead.bind(("127.0.0.1", 0))
        dead_port = dead.getsockname()[1]
        dead.close()  # nothing listens here now
        try:
            probe = heartbeat_capacity_probe(
                8, ports=[live_port, dead_port], timeout=0.5)
            assert probe() == 4  # 8 * 1 // 2
        finally:
            live.close()

    def test_empty_registry_reads_full_capacity(self):
        assert heartbeat_capacity_probe(8, ports=[])() == 8

    def test_negative_total_rejected(self):
        with pytest.raises(ValueError):
            heartbeat_capacity_probe(-1, ports=[])


class TestFileCapacityFeed:
    def test_round_trip_and_failures_raise(self, tmp_path):
        feed = FileCapacityFeed(tmp_path / "cap.txt")
        with pytest.raises(OSError):
            feed()  # missing file: the watch contains this, not the feed
        feed.write(5)
        assert feed() == 5
        Path(feed.path).write_text("not-a-number\n")
        with pytest.raises(ValueError):
            feed()


class TestProbeContainment:
    def test_raising_probe_degrades_to_last_committed(self, stream):
        """The containment satellite: a feed that works once then breaks
        costs staleness (last committed reading) plus a loud counter —
        never an exception out of available()."""
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] > 1:
                raise RuntimeError("feed endpoint down")
            return 5

        watch = CapacityWatch(total=8, probe=flaky)
        assert watch.available() == 5
        assert watch.available() == 5   # degraded, not crashed
        errs = [e for e in _tail() if e.get("kind") == "counter"
                and e.get("name") == "capacity_probe_errors"]
        assert errs and errs[-1]["error"] == "RuntimeError"

    def test_raising_probe_never_kills_poll_grow(self, stream):
        watch = CapacityWatch(total=8, probe=lambda: 1 / 0)
        # poll path survives and answers off the committed count (8 > 4)
        assert watch.poll_grow(4) == 8
        assert watch.poll_grow(8) is None

    def test_probe_readings_clamped_to_total(self):
        assert CapacityWatch(total=8, probe=lambda: 999).available() == 8
        assert CapacityWatch(total=8, probe=lambda: -3).available() == 0

    def test_hanging_probe_times_out_fast(self, stream):
        """With probe_timeout_s armed, a hung feed degrades within the
        budget (boxed on the dpt-capacity-probe worker) and the NEXT
        poll fails fast instead of queueing behind the wedged call."""
        release = threading.Event()

        def hang():
            release.wait(30.0)
            return 3

        before = _probe_threads()
        watch = CapacityWatch(total=8, probe=hang, probe_timeout_s=0.2)
        t0 = time.monotonic()
        assert watch.available() == 8       # degraded to committed
        assert watch.available() == 8       # fail-fast on the stale call
        assert time.monotonic() - t0 < 5.0
        assert _probe_threads() == before + 1
        errs = [e for e in _tail() if e.get("kind") == "counter"
                and e.get("name") == "capacity_probe_errors"]
        assert len(errs) >= 2
        assert all(e["error"] == "TimeoutError" for e in errs[-2:])
        release.set()  # let the boxed call finish; worker parks on its queue

    def test_no_timeout_means_no_worker_thread(self):
        """The autopilot-off thread pin: a plain probe (no timeout) is a
        direct call — zero threads appear."""
        before = _probe_threads()
        watch = CapacityWatch(total=8, probe=lambda: 6)
        assert watch.available() == 6
        assert _probe_threads() == before


# ---------------------------------------------------------------------------
# loop (2): the contract gate + apply_decision
# ---------------------------------------------------------------------------


class TestContractGate:
    def test_non_tunable_key_refused_without_lowering(self):
        ok, refusals = contract_gate({"learning_rate": 0.1})
        assert ok is False
        assert "non-tunable" in refusals[0]
        assert all(k in TUNABLE_KEYS for k in DEFAULT_CANDIDATE)

    def test_unloweable_config_refused_not_raised(self):
        ok, refusals = contract_gate({"wire_dtype": "no_such_wire"})
        assert ok is False and refusals

    def test_default_candidate_passes_the_real_matrix(self):
        """The candidate the tuner actually proposes (int8 multihop +
        tiny bucket cap) must clear the full HLO rule set over the
        control_replan base — the gate's approve leg, lowered for real."""
        ok, refusals = contract_gate(dict(DEFAULT_CANDIDATE))
        assert ok is True, refusals


class _StubSup:
    """A Supervisor-shaped stub: scripted boundary_* results, recorded
    calls — apply_decision's contract without a mesh."""

    def __init__(self, world=8, shrink=None, retune=None):
        self._world = world
        self._shrink = shrink
        self._retune = retune
        self.calls = []

    @property
    def world_size(self):
        return self._world

    def boundary_shrink(self, report, state, *, epoch, step,
                        evicted_rank=None, cause=""):
        self.calls.append(("shrink", evicted_rank, cause))
        new_state, applied, detail, new_world = self._shrink
        if applied:
            self._world = new_world
        return new_state, applied, detail

    def boundary_retune(self, report, state, *, epoch, step, overrides,
                        cause=""):
        self.calls.append(("retune", dict(overrides), cause))
        new_state, applied, detail = self._retune
        return new_state, applied, detail


class TestApplyDecision:
    def test_observation_actions_are_not_applicable(self, stream):
        sup = _StubSup()
        with pytest.raises(ValueError, match="not applicable"):
            apply_decision(sup, ControlDecision(action="detect", reason="r"),
                           report=None, state="s", epoch=0, step=1)

    def test_evict_applied_records_worlds_and_canonical_cause(self, stream):
        sup = _StubSup(world=8, shrink=("new", True, "", 4))
        state, final = apply_decision(
            sup, ControlDecision(action="evict", reason="free text", rank=3),
            report=None, state="old", epoch=1, step=4)
        assert state == "new"
        assert final.applied and final.action == "evict"
        assert (final.world_from, final.world_to) == (8, 4)
        # the resize record's cause is the canonical tag, never free text
        assert sup.calls == [("shrink", 3, "straggler_evict")]
        names = [e.get("name") for e in _tail()]
        assert "evict" in names and "control_apply" in names

    def test_evict_refusal_emits_refuse_and_keeps_state(self, stream):
        sup = _StubSup(world=2, shrink=("old", False, "cannot shrink "
                                        "below one replica", 2))
        state, final = apply_decision(
            sup, ControlDecision(action="evict", reason="r", rank=0),
            report=None, state="old", epoch=0, step=2)
        assert state == "old" and final.action == "refuse"
        assert final.applied is False
        assert final.evidence["refused_action"] == "evict"
        assert "cannot shrink" in final.evidence["refusals"][0]

    def test_retune_without_overrides_refused(self, stream):
        sup = _StubSup(retune=("new", True, ""))
        _, final = apply_decision(
            sup, ControlDecision(action="retune", reason="r"),
            report=None, state="s", epoch=0, step=2)
        assert final.action == "refuse" and sup.calls == []

    def test_failing_gate_refuses_before_the_replan_surface(self, stream):
        """The acceptance clause: a candidate failing its contract is
        REFUSED AND LOGGED — boundary_retune is never reached."""
        sup = _StubSup(retune=("new", True, ""))
        _, final = apply_decision(
            sup, ControlDecision(action="retune", reason="r",
                                 evidence={"overrides": {"wire_dtype":
                                                         "int8"}}),
            report=None, state="s", epoch=0, step=2,
            gate=lambda o: (False, ["exactness finding: drift"]))
        assert final.action == "refuse" and sup.calls == []
        assert final.evidence["refusals"] == ["exactness finding: drift"]
        refuses = [e for e in _tail()
                   if e.get("kind") == CONTROL_DECISION_KIND
                   and e.get("name") == "refuse"]
        assert refuses, "a refused candidate must still be on the stream"

    def test_passing_gate_commits_the_retune(self, stream):
        sup = _StubSup(world=8, retune=("new", True, ""))
        state, final = apply_decision(
            sup, ControlDecision(action="retune", reason="comm-bound",
                                 evidence={"overrides": {"wire_dtype":
                                                         "bf16"}}),
            report=None, state="s", epoch=0, step=2,
            gate=lambda o: (True, []))
        assert state == "new" and final.applied
        assert sup.calls == [("retune", {"wire_dtype": "bf16"},
                              "comm-bound")]


# ---------------------------------------------------------------------------
# the tuner
# ---------------------------------------------------------------------------


class TestPerfTuner:
    def _window(self, ratio):
        return {"kind": DEVICE_PROFILE_KIND, "exposed_comm_ratio": ratio}

    def test_proposes_once_above_threshold(self):
        t = PerfTuner(threshold=0.3, min_windows=2)
        t.observe(self._window(0.5))
        assert t.propose() is None          # one window is weather
        t.observe(self._window(0.7))
        p = t.propose()
        assert p["overrides"] == DEFAULT_CANDIDATE
        assert p["evidence"]["windows"] == 2
        assert p["evidence"]["mean_exposed_comm_ratio"] == 0.6
        assert t.propose() is None          # one-shot until reset
        t.reset()
        assert t.windows == 0

    def test_below_threshold_or_wrong_kind_is_quiet(self):
        t = PerfTuner(threshold=0.5, min_windows=1)
        t.observe({"kind": "span", "exposed_comm_ratio": 0.9})
        t.observe(self._window(0.2))
        assert t.propose() is None

    def test_already_on_candidate_wire_is_quiet(self):
        t = PerfTuner(threshold=0.1, min_windows=1)
        t.observe(self._window(0.9))
        assert t.propose({"wire_dtype": "int8_multihop"}) is None

    def test_invalid_candidate_keys_rejected(self):
        with pytest.raises(ValueError, match="not.*tunable"):
            PerfTuner(candidate={"learning_rate": 0.1})


# ---------------------------------------------------------------------------
# the autopilot object
# ---------------------------------------------------------------------------


class TestAutopilotUnit:
    def test_attach_requires_configured_telemetry(self):
        telemetry.reset()
        with pytest.raises(RuntimeError, match="configured telemetry"):
            Autopilot().attach()

    def test_observer_buffers_only_policy_phases(self, stream):
        ap = Autopilot().attach()
        try:
            telemetry.span_event("data_wait", 0.9, step=5)
            telemetry.span_event("forward", 0.9, step=5)
            telemetry.emit(CONTROL_DECISION_KIND, "detect", reason="r")
            buffered = ap._drain()
            assert [e["name"] for e in buffered] == ["data_wait"]
        finally:
            ap.detach()
        telemetry.span_event("data_wait", 0.9, step=6)
        assert len(ap._drain()) == 1  # detached: nothing new buffered

    def test_readmission_emits_the_grow_decision(self, stream):
        """World back at the pre-eviction size -> one applied grow
        decision, suspension lifted, history cleared."""
        ap = Autopilot().attach()
        try:
            ap._last_world = 4
            ap._pending_readmit = 8
            ap._evicted_rank = 3
            ap.policy.observe_rows([_row(5), _row(6), _row(7)])
            state = ap.on_segment_boundary(
                supervisor=_StubSup(world=8), report=None, state="s",
                epoch=1, step=12)
            assert state == "s"
            (grow,) = ap.decisions
            assert grow.action == "grow" and grow.applied
            assert (grow.world_from, grow.world_to) == (4, 8)
            assert grow.rank == 3
            assert ap._pending_readmit is None
            # stale pre-grow history must not convict the renumbered rank
            assert ap.policy.verdict() is None
        finally:
            ap.detach()

    def test_detection_suspended_while_capacity_is_out(self, stream):
        ap = Autopilot().attach()
        try:
            ap._last_world = 4
            ap._pending_readmit = 8
            telemetry.span_event("data_wait", 5.0, step=9)
            ap.on_segment_boundary(supervisor=_StubSup(world=4),
                                   report=None, state="s", epoch=1, step=10)
            assert ap.decisions == []  # no detect while shrunken
        finally:
            ap.detach()


# ---------------------------------------------------------------------------
# live Supervisor legs (the 8-device CPU mesh)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def rig8(mesh8):
    from distributed_pytorch_training_tpu.resilience.__main__ import (
        _build_rig,
    )

    # dataset 32 / global batch 16 -> 2 steps per epoch
    return _build_rig(mesh8, seed=0, dataset_size=32, per_device_batch=2)


def _retune_supervisor(rig8, mesh8, tuner_gate):
    from distributed_pytorch_training_tpu.resilience.__main__ import (
        _build_rig,
    )
    from distributed_pytorch_training_tpu.resilience.elastic import (
        ElasticPlan,
    )
    from distributed_pytorch_training_tpu.resilience.supervisor import (
        Supervisor,
    )

    trainer, state_factory, loader = rig8
    tuner = PerfTuner(threshold=0.1, min_windows=1,
                      candidate={"wire_dtype": "bf16"})
    tuner.observe({"kind": DEVICE_PROFILE_KIND, "exposed_comm_ratio": 0.8})
    ap = Autopilot(tuner=tuner, evict=False, gate=tuner_gate).attach()

    def retune_cb(overrides):
        t, sf, ld = _build_rig(mesh8, seed=0, dataset_size=32,
                               per_device_batch=2,
                               wire_dtype=overrides["wire_dtype"])
        return ElasticPlan(trainer=t, loader=ld, state_factory=sf, world=8)

    sup = Supervisor(trainer, None, state_factory, loader,
                     checkpoint_every_steps=1, retune_cb=retune_cb,
                     control=ap)
    return sup, ap


class TestBoundaryRetuneLive:
    def test_gated_retune_applies_at_the_boundary(self, stream, rig8,
                                                  mesh8):
        """Loop (2) live: the tuner's proposal passes its (stubbed) gate
        and the run continues at the same world on the new wire — the
        re-plan landing ONLY at the segment boundary, moments carried,
        no state leaf reset (bf16 wire adds no EF buffers)."""
        sup, ap = _retune_supervisor(rig8, mesh8,
                                     tuner_gate=lambda o: (True, []))
        try:
            state, report = sup.run(1)
        finally:
            ap.detach()
        assert report.completed and report.final_step == 2
        (rec,) = report.retunes
        assert rec["overrides"] == {"wire_dtype": "bf16"}
        assert (rec["epoch"], rec["step"]) == (0, 1)  # the mid-epoch anchor
        assert rec["resets"] == []
        assert sup.trainer.config.wire_dtype == "bf16"
        assert sup.world_size == 8  # a retune never changes capacity
        final = ap.decisions[-1]
        assert final.action == "retune" and final.applied
        spans = [e.get("name") for e in _tail(500)
                 if e.get("kind") == "span"]
        assert "control_retune" in spans and "control_apply" in spans

    def test_refused_candidate_leaves_the_run_on_the_old_config(
            self, stream, rig8, mesh8):
        """The refusal twin: a failing contract refuses the candidate
        with a logged decision and the run COMPLETES on fp32 — refusal
        is an audit event, never an error."""
        sup, ap = _retune_supervisor(
            rig8, mesh8,
            tuner_gate=lambda o: (False, ["hlo finding: wire drift"]))
        try:
            state, report = sup.run(1)
        finally:
            ap.detach()
        assert report.completed and report.final_step == 2
        assert report.retunes == []
        assert sup.trainer.config.wire_dtype == "fp32"
        (refuse,) = [d for d in ap.decisions if d.action == "refuse"]
        assert refuse.evidence["refused_action"] == "retune"
        assert refuse.evidence["refusals"] == ["hlo finding: wire drift"]


class TestAutopilotOffPin:
    def test_control_none_leaves_no_trace(self, stream, rig8):
        """Off by default, NOTHING when off: a control=None supervised
        run emits zero control events/spans and starts zero probe
        threads — the stream is indistinguishable from a build without
        the control package."""
        from distributed_pytorch_training_tpu.resilience.supervisor import (
            Supervisor,
        )

        trainer, state_factory, loader = rig8
        before = _probe_threads()
        sup = Supervisor(trainer, None, state_factory, loader,
                         checkpoint_every_steps=1, control=None)
        state, report = sup.run(1)
        assert report.completed
        evs = _tail(1000)
        assert not [e for e in evs
                    if e.get("kind") == CONTROL_DECISION_KIND]
        assert not [e for e in evs if e.get("kind") == "span"
                    and e.get("name") in ("control_apply",
                                          "control_retune")]
        assert _probe_threads() == before


# ---------------------------------------------------------------------------
# the acceptance e2e: chaos --autopilot, then every renderer of its stream
# ---------------------------------------------------------------------------


class TestAutopilotChaosE2E:
    def test_detect_evict_grow_chain_with_bitwise_parity(self, tmp_path,
                                                         capsys):
        """ISSUE 20 acceptance: a persistent loader_stall straggler is
        detected from the stream, evicted at a segment boundary (shrink
        8 -> 4 via the elastic path — NO fault raised, zero restarts),
        the returned capacity re-admitted by the boundary grow, and the
        post-resize segment is BITWISE equal to a clean continuation.
        The decision chain must be readable back off the stream file,
        and the same artifact must render through /metrics and
        `telemetry summary`."""
        from distributed_pytorch_training_tpu.resilience.__main__ import (
            main,
        )

        rc = main(["chaos", "--autopilot", "--ckpt-dir", str(tmp_path),
                   "--json"])
        stats = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])
        assert rc == 0
        assert stats["autopilot"] is True
        assert stats["completed"] is True
        assert stats["parity_bitwise"] is True
        # nothing crashed: the ONLY path to the resize was the control
        # plane naming the straggler
        assert stats["restarts"] == 0
        assert [r["direction"] for r in stats["resizes"]] == \
            ["shrink", "grow"]
        shrink = stats["resizes"][0]
        assert shrink["cause"] == "straggler_evict"
        assert (shrink["from_world"], shrink["to_world"]) == (8, 4)
        assert shrink["evicted_rank"] is not None
        grow = stats["resizes"][1]
        assert (grow["from_world"], grow["to_world"]) == (4, 8)

        decisions = stats["control_decisions"]
        actions = [d["action"] for d in decisions]
        assert "detect" in actions and "grow" in actions
        evict = next(d for d in decisions if d["action"] == "evict")
        assert evict["applied"] is True
        assert (evict["world_from"], evict["world_to"]) == (8, 4)
        assert evict["rank"] == shrink["evicted_rank"]
        # detect precedes its evict; the grow closes the chain
        assert actions.index("detect") < actions.index("evict")
        assert actions.index("evict") < actions.index("grow")
        assert stats["flights_ok"] is True

        # --- the renderers, fed the run's OWN stream artifact ---------
        stream_path = Path(stats["ckpt_dir"]) / "telemetry_rank0.jsonl"
        events = [json.loads(line) for line in
                  stream_path.read_text().splitlines()]

        from distributed_pytorch_training_tpu.telemetry.metrics_http import (
            _MetricsState,
        )

        ms = _MetricsState()
        for ev in events:
            ms.observe(ev)
        page = ms.render()
        assert 'dpt_control_decisions_total{action="evict"} 1' in page
        assert 'dpt_control_decisions_total{action="detect"}' in page
        assert 'dpt_control_decisions_total{action="grow"} 1' in page

        from distributed_pytorch_training_tpu.telemetry.__main__ import (
            summarize,
        )

        s = summarize(events)["control_decisions"]
        assert s["total"] == len(decisions)
        assert s["by_action"]["evict"] == 1
        assert [c["action"] for c in s["chain"]] == actions
        # the decision spans are accounted next to their verdicts
        assert summarize(events)["spans"].get("control_apply",
                                              {}).get("count", 0) >= 1
