"""Explicit tensor parallelism x FSDP on the 2-D ("data","model") mesh
(training/loop.py `_fsdp_step` with `_tp_n` > 1; ISSUE 13).

The contract (acceptance): (a) 20-step fp32 parity on the CPU mesh —
data=2,model=2 TP x FSDP matches the 1-D replicated baseline at the
PARITY.md reassociation tolerance, grad-accum on AND off, and
int8_multihop converges with EF present; (b) at-rest census — params AND
both AdamW moments flat-sharded 1/(N*M) for every TP-split leaf (the
model-major layout, parallel/sharding.tp_flat_leaf); (c) HLO census —
exactly the megatron model-axis psum budget (one per residual join
forward + its backward mirror, +2 for the vocab-parallel embedding, +2
for the parallel-vocab CE's batch-shaped stat collectives when they
clear the floor — ISSUE 16), ZERO model-axis gathers (the vocab-scale
logits gather is the regression the parallel-vocab cross-entropy
removed), one DATA-axis gather and one scatter per layer
group over the TP-LOCAL plan, and ZERO gradient-sized all-reduce off the
model axis (floor-aware, per-group); (d) the `fsdp_tp` contracts evaluate
clean in the default `analysis check` gate, and each new rule flags a
synthetic violation (mutation tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_training_tpu.models.gpt2 import GPT2LMHead
from distributed_pytorch_training_tpu.parallel import (
    MeshSpec, build_mesh, shard_batch,
)
from distributed_pytorch_training_tpu.parallel.grad_sync import (
    build_layer_plan, tp_psum_bytes_per_step, wire_bytes_for_config,
)
from distributed_pytorch_training_tpu.parallel.mesh import BATCH_AXES, MODEL
from distributed_pytorch_training_tpu.parallel.sharding import (
    tp_clip_weights, tp_flat_leaf, tp_local_struct, tp_split_dims,
    tp_unflatten_leaf,
)
from distributed_pytorch_training_tpu.training import TrainConfig, Trainer
from distributed_pytorch_training_tpu.training.optim import adamw, sgd
from distributed_pytorch_training_tpu.training.tasks import LanguageModelingTask

SEQ = 16
VOCAB = 64  # divisible by the TP degrees below: the vocab-parallel path engages
HIDDEN, DEPTH, HEADS = 32, 2, 2
TP_AXES = (MODEL,) + BATCH_AXES


def _tiny_gpt2():
    return GPT2LMHead(vocab_size=VOCAB, hidden_dim=HIDDEN, depth=DEPTH,
                      num_heads=HEADS, max_position=SEQ)


@pytest.fixture(scope="module")
def mesh_tp(devices):
    return build_mesh(MeshSpec(data=2, model=2), devices=devices[:4])


@pytest.fixture(scope="module")
def mesh_1d(devices):
    return build_mesh(MeshSpec(data=4), devices=devices[:4])


def _split_plan(model_n=2):
    tmpl = jax.eval_shape(
        lambda: _tiny_gpt2().init(jax.random.PRNGKey(0),
                                  jnp.zeros((2, SEQ), jnp.int32),
                                  train=False))["params"]
    sd = tp_split_dims(tmpl, GPT2LMHead.partition_rules(), model_n)
    return tmpl, sd


def _make_tx(opt, tp):
    if opt == "sgd":
        return sgd(0.1, momentum=0.9, weight_decay=5e-4)
    # active global-norm clip: under TP the norm psums over
    # (model,) + batch axes with model-replicated leaves weighted 1/M
    if not tp:
        return adamw(1e-2, grad_clip_norm=1.0)
    tmpl, sd = _split_plan()
    return adamw(1e-2, grad_clip_norm=1.0, shard_axes=TP_AXES,
                 clip_leaf_weights=tp_clip_weights(tmpl, sd, 2))


def _trainer(mesh, opt, fsdp, wire="fp32", grad_accum=1):
    tp = fsdp and dict(mesh.shape).get(MODEL, 1) > 1
    t = Trainer(LanguageModelingTask(compute_dtype=jnp.float32), mesh,
                TrainConfig(seed=0, fsdp_explicit=fsdp, wire_dtype=wire,
                            grad_accum=grad_accum),
                rules=GPT2LMHead.partition_rules() if fsdp else None)
    s = t.init_state(_tiny_gpt2(), np.zeros((1, SEQ), np.int32),
                     _make_tx(opt, tp), jax.random.PRNGKey(0))
    return t, s


def _batch(mesh, n=16):
    rng = np.random.RandomState(0)
    return shard_batch({
        "input_ids": rng.randint(0, VOCAB, (n, SEQ)).astype(np.int32),
        "weight": np.ones(n, np.float32)}, mesh)


def _run(mesh, opt, fsdp, steps=20, wire="fp32", grad_accum=1):
    batch = _batch(mesh)
    key = jax.random.PRNGKey(1)
    t, s = _trainer(mesh, opt, fsdp, wire=wire, grad_accum=grad_accum)
    losses = []
    for _ in range(steps):
        s, m = t._train_step(s, batch, key)
        losses.append(float(m["loss_sum"]) / max(float(m["weight"]), 1.0))
    return losses, s, t


def _full_params(t, s):
    return t._fsdp_unflatten(s.params) if t._fsdp else s.params


def _assert_params_close(ref, got, **tol):
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b)),
            **tol),
        ref, got)


# --- fp32 parity vs the 1-D replicated baseline -----------------------------


@pytest.mark.slow  # ~11 s; the adamw+clip 20-step leg stays fast and is the stricter parity
def test_tp_fsdp_sgd_20step_matches_replicated(mesh_1d, mesh_tp):
    """THE acceptance parity: same global batch, same seed — the 2-D
    TP x FSDP trajectory matches the replicated 1-D baseline at
    reassociation tolerance (the megatron split reorders contractions,
    never the math)."""
    l_rep, s_rep, t_rep = _run(mesh_1d, "sgd", fsdp=False)
    l_tp, s_tp, t_tp = _run(mesh_tp, "sgd", fsdp=True)
    np.testing.assert_allclose(l_rep, l_tp, rtol=2e-5)
    # 20 steps of reassociated contractions accumulate ~1e-6-level drift
    # on ~1e-4-magnitude weights — atol sized to that, rtol unchanged
    _assert_params_close(_full_params(t_rep, s_rep),
                         _full_params(t_tp, s_tp), rtol=1e-4, atol=5e-6)
    assert l_rep[-1] < l_rep[0]


@pytest.mark.slow  # ~13 s; the adamw+clip non-accum parity stays fast and the accum lowering is gated by the fsdp_accum matrix contract
def test_tp_fsdp_grad_accum_matches_replicated_grad_accum(mesh_1d, mesh_tp):
    """grad_accum=2: the per-layer scatters run inside the microbatch scan
    with the TP forward; trajectory parity must hold unchanged."""
    l_rep, s_rep, t_rep = _run(mesh_1d, "sgd", fsdp=False, grad_accum=2)
    l_tp, s_tp, t_tp = _run(mesh_tp, "sgd", fsdp=True, grad_accum=2)
    np.testing.assert_allclose(l_rep, l_tp, rtol=2e-5)
    _assert_params_close(_full_params(t_rep, s_rep),
                         _full_params(t_tp, s_tp), rtol=1e-4, atol=5e-6)


def test_tp_fsdp_adamw_clip_matches_replicated(mesh_1d, mesh_tp):
    """AdamW with the global-norm clip ACTIVE: the TP-aware clip psums
    squared norms over (model,) + batch axes with model-replicated leaves
    down-weighted 1/M (tp_clip_weights) — the recovered norm must equal
    the replicated run's exactly (M=2 is a power of two: the 1/M weights
    are exact in fp32)."""
    l_rep, s_rep, t_rep = _run(mesh_1d, "adamw", fsdp=False, steps=6)
    l_tp, s_tp, t_tp = _run(mesh_tp, "adamw", fsdp=True, steps=6)
    np.testing.assert_allclose(l_rep, l_tp, rtol=2e-5)
    _assert_params_close(_full_params(t_rep, s_rep),
                         _full_params(t_tp, s_tp), rtol=2e-2, atol=2e-3)


@pytest.mark.slow  # ~8 s convergence smoke; the fsdp_tp_int8_mh matrix contract + the 1-D fsdp int8 EF legs stay fast
def test_tp_fsdp_int8_multihop_converges_with_ef(mesh_tp):
    """The fully compressed wire under TP: s8 data-axis gradient scatter
    with error feedback per (model shard, data replica) pair + s8 param
    gathers; model-axis psums stay exact fp32. Convergence + EF present,
    not fp32 parity (PARITY.md exactness model)."""
    l_fp32, _, _ = _run(mesh_tp, "sgd", fsdp=True, steps=8)
    l_mh, s_mh, t_mh = _run(mesh_tp, "sgd", fsdp=True, steps=8,
                            wire="int8_multihop")
    assert l_mh[-1] < l_mh[0]
    np.testing.assert_allclose(l_fp32, l_mh, rtol=2e-2)
    plan = t_mh._fsdp_plan
    assert set(s_mh.grad_sync["ef"].keys()) == {g.name for g in plan.groups}
    for name, r in s_mh.grad_sync["ef"].items():
        # model-major rows: one per (model shard, data replica) pair
        assert r.shape == (2 * 2, 2 * dict(
            (g.name, g.row_size) for g in plan.groups)[name]), (name,
                                                                r.shape)
    total = sum(float(jnp.abs(r).sum())
                for r in jax.tree_util.tree_leaves(s_mh.grad_sync["ef"]))
    assert total > 0.0


def test_tp_eval_step_matches_replicated_eval(mesh_1d, mesh_tp):
    """Eval unflattens the model-major at-rest layout outside shard_map
    (split leaves re-concatenate along their split dim) and runs the full
    model — same loss as the replicated eval on the same params."""
    t_rep, s_rep = _trainer(mesh_1d, "sgd", fsdp=False)
    t_tp, s_tp = _trainer(mesh_tp, "sgd", fsdp=True)
    m_rep = t_rep._eval_step(s_rep, _batch(mesh_1d))
    m_tp = t_tp._eval_step(s_tp, _batch(mesh_tp))
    np.testing.assert_allclose(float(m_rep["loss_sum"]),
                               float(m_tp["loss_sum"]), rtol=1e-5)


def test_tp_parallel_ce_matches_gathered_fp32():
    """The parallel-vocab CE pin (ISSUE 16): loss, gradient and the
    correctness mask computed from LOCAL logit columns (2 batch-shaped
    model-axis stats) match the gathered-logits optax form in fp32, and
    both shards return the identical replicated value."""
    import optax

    from distributed_pytorch_training_tpu.parallel.collectives import (
        TpShardedLogits, tp_parallel_cross_entropy,
    )

    rng = np.random.RandomState(0)
    full = (rng.randn(4, 7, VOCAB) * 4.0).astype(np.float32)
    tgt = rng.randint(0, VOCAB, (4, 7)).astype(np.int32)
    half = VOCAB // 2

    def per_shard(local):
        return tp_parallel_cross_entropy(
            TpShardedLogits(local, "m", half, VOCAB), jnp.asarray(tgt))

    locals_ = jnp.stack([full[..., :half], full[..., half:]])
    ce, correct = jax.vmap(per_shard, axis_name="m")(locals_)
    ref = optax.softmax_cross_entropy_with_integer_labels(
        jnp.asarray(full), jnp.asarray(tgt))
    np.testing.assert_array_equal(np.asarray(ce[0]), np.asarray(ce[1]))
    np.testing.assert_allclose(np.asarray(ce[0]), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(
        np.asarray(correct[0]), np.asarray(jnp.argmax(full, -1) == tgt))

    # gradient parity: d(sum ce)/d(logits) — softmax minus one-hot,
    # each shard holding exactly its own columns of the gathered grad
    g_sharded = jax.vmap(
        lambda l: jax.grad(lambda x: per_shard(x)[0].sum())(l),
        axis_name="m")(locals_)
    g_ref = jax.grad(
        lambda x: optax.softmax_cross_entropy_with_integer_labels(
            x, jnp.asarray(tgt)).sum())(jnp.asarray(full))
    np.testing.assert_allclose(np.asarray(g_sharded[0]),
                               np.asarray(g_ref[..., :half]),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(g_sharded[1]),
                               np.asarray(g_ref[..., half:]),
                               rtol=1e-5, atol=1e-6)


# --- at-rest census ---------------------------------------------------------


def test_tp_at_rest_params_and_moments_1_over_nm(mesh_tp):
    """Params AND both AdamW moments live model-major flat-sharded: every
    TP-split leaf holds exactly local_size/(N) elements per device =
    1/(N*M) of the full tensor (padding aside); model-replicated leaves
    (layernorms, row-parallel biases, wpe) hold 1/N per device — and the
    TP-split leaves carry the BULK of the bytes (the embedding splits)."""
    t, state = _trainer(mesh_tp, "adamw", fsdp=True)
    tmpl, sd = _split_plan()
    split_bytes = repl_bytes = 0
    n_split = 0
    for tree in (state.params, state.opt_state[1].mu, state.opt_state[1].nu):
        for (path, leaf), (_, full), (_, d) in zip(
                jax.tree_util.tree_leaves_with_path(tree),
                jax.tree_util.tree_leaves_with_path(tmpl),
                jax.tree_util.tree_leaves_with_path(
                    sd, is_leaf=lambda x: x is None)):
            full_size = int(np.prod(full.shape) or 1)
            local = full_size // 2 if d is not None else full_size
            padded = local + (-local % 2)
            assert leaf.ndim == 1 and leaf.shape == (2 * padded,), (
                path, leaf.shape)
            assert not leaf.sharding.is_fully_replicated, path
            shard = leaf.addressable_shards[0].data
            # per-DEVICE residency: padded_local / N — 1/(N*M) of the
            # full tensor for split leaves
            assert shard.shape == (padded // 2,), (path, shard.shape)
            if d is not None:
                n_split += 1
                split_bytes += full_size
            else:
                repl_bytes += full_size
    assert n_split >= 3 * 13  # 13 split leaves per tree (incl. wte)
    assert split_bytes > 4 * repl_bytes  # the split leaves are the bulk


def test_tp_flat_leaf_round_trips_and_layout_is_model_major():
    rng = np.random.RandomState(0)
    x = rng.randn(12, 6).astype(np.float32)
    flat = np.asarray(tp_flat_leaf(jnp.asarray(x), 0, 3, 2))
    # model-major: segment s is slice s, flat-padded over N=2
    for s in range(3):
        np.testing.assert_array_equal(
            flat[s * 24:(s + 1) * 24], x[s * 4:(s + 1) * 4].ravel())
    back = np.asarray(tp_unflatten_leaf(jnp.asarray(flat), (12, 6),
                                        np.float32, 0, 3))
    np.testing.assert_array_equal(back, x)


def test_tp_split_dims_follow_rules_and_degrade_on_indivisible():
    tmpl, sd = _split_plan()
    flat = {jax.tree_util.keystr(p): d for p, d in
            jax.tree_util.tree_leaves_with_path(
                sd, is_leaf=lambda x: x is None)}
    assert flat["['wte']['embedding']"] == 0          # vocab-parallel
    assert flat["['wpe']['embedding']"] is None
    assert flat["['block0']['attn']['qkv']['kernel']"] == 2
    assert flat["['block0']['attn']['out']['kernel']"] == 0
    assert flat["['block0']['mlp']['fc1']['kernel']"] == 1
    assert flat["['block0']['mlp']['fc2']['kernel']"] == 0
    assert flat["['block0']['ln1']['scale']"] is None
    # indivisible vocab degrades the embedding (Megatron padding absent)
    model = GPT2LMHead(vocab_size=50257, hidden_dim=32, depth=1,
                       num_heads=2, max_position=SEQ)
    tmpl2 = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0),
                           jnp.zeros((2, SEQ), jnp.int32),
                           train=False))["params"]
    sd2 = tp_split_dims(tmpl2, GPT2LMHead.partition_rules(), 2)
    assert sd2["wte"]["embedding"] is None
    assert not model.clone(tp_size=2, tp_axis=MODEL).tp_vocab


def test_tp_clip_weights_mark_duplicated_leaves():
    tmpl, sd = _split_plan()
    w = tp_clip_weights(tmpl, sd, 2)
    assert w["wte/embedding"] == 1.0
    assert w["wpe/embedding"] == 0.5
    assert w["block0/mlp/fc2/kernel"] == 1.0
    assert w["block0/mlp/fc2/bias"] == 0.5
    assert w["ln_f/scale"] == 0.5
    # every leaf classified — a missing path would silently mis-weight
    assert len(w) == len(jax.tree_util.tree_leaves(tmpl))


# --- HLO census -------------------------------------------------------------


def _axis_counts(text, floor, n_batch, n_model):
    from distributed_pytorch_training_tpu.analysis.hlo_rules import (
        grad_sync_census, replica_group_axis,
    )

    out = {}
    for r in grad_sync_census(text, min_elements=floor)["rows"]:
        ax = replica_group_axis(r["replica_groups"], n_batch, n_model)
        key = (r["op"], ax)
        out[key] = out.get(key, 0) + r["count"]
    return out


@pytest.mark.parametrize("wire", [
    "fp32",
    # ~5 s; strictly redundant with the fsdp_tp_int8_mh contract in the
    # matrix gate — the fp32 arm keeps the census shape pinned fast
    pytest.param("int8_multihop", marks=pytest.mark.slow),
])
def test_tp_census_model_psums_and_data_only_wire(mesh_tp, wire):
    """The acceptance census: exactly 4*depth + 2 model-axis psums (one
    per residual join forward + backward mirror, + the vocab-parallel
    embedding pair) + 2 parallel-vocab CE stat collectives (the pmax +
    the stacked sumexp/target psum — batch-shaped (rows, S-1, 2) = 240
    elements here, over the 64 floor), ZERO model-axis gathers (the
    vocab-scale logits gather is gone), one DATA-axis gather and one
    scatter per layer group over the TP-LOCAL plan, and zero
    gradient-sized all-reduce off the model axis — floor-aware,
    per-group."""
    floor = 64
    t, s = _trainer(mesh_tp, "sgd", fsdp=True, wire=wire)
    text = t._train_step.lower(
        s, _batch(mesh_tp), jax.random.PRNGKey(1)).compile().as_text()
    counts = _axis_counts(text, floor, n_batch=2, n_model=2)

    assert counts.get(("all-reduce", "model"), 0) == 4 * DEPTH + 2 + 2
    assert counts.get(("all-gather", "model"), 0) == 0  # no logits gather
    assert counts.get(("all-reduce", "data"), 0) == 0
    assert counts.get(("all-reduce", "all"), 0) == 0

    plan = t._fsdp_plan
    sizes = [2 * g.row_size for g in plan.groups]
    exp_gathers = sum(1 for sz in sizes if sz >= floor)
    assert exp_gathers >= 4  # the floor must not trivialize the census
    assert counts.get(("all-gather", "data"), 0) == exp_gathers
    if wire == "int8_multihop":
        exp_scatter = sum(1 for sz in sizes if sz >= floor)
        got = counts.get(("all-to-all", "data"), 0)
    else:
        exp_scatter = sum(1 for sz in sizes if sz // 2 >= floor)
        got = counts.get(("reduce-scatter", "data"), 0)
    assert got == exp_scatter, counts
    # nothing rides groups spanning the whole mesh
    assert not any(ax in ("all", "other", "unknown")
                   for (_op, ax) in counts), counts


def test_tp_layer_plan_is_local(mesh_tp):
    """The layer plan cuts the TP-LOCAL template: per-group row sizes are
    1/M of the 1-D plan's for fully-split groups (the 1/M gather/scatter
    wire reduction, as layout arithmetic)."""
    t, _ = _trainer(mesh_tp, "sgd", fsdp=True)
    tmpl, sd = _split_plan()
    local = tp_local_struct(tmpl, sd, 2)
    expect = build_layer_plan(local, 2)
    assert [g.name for g in t._fsdp_plan.groups] == \
        [g.name for g in expect.groups]
    assert [g.row_size for g in t._fsdp_plan.groups] == \
        [g.row_size for g in expect.groups]
    full_plan = build_layer_plan(tmpl, 2)
    by_name = {g.name: g.row_size for g in full_plan.groups}
    wte_local = {g.name: g.row_size for g in expect.groups}["wte"]
    assert wte_local == by_name["wte"] // 2  # the embedding really halves


# --- analysis contracts + mutation tests ------------------------------------


@pytest.mark.slow  # ~8 s; strictly redundant with the full contract-matrix gate in test_analysis_cli
def test_fsdp_tp_contracts_pass_without_relaxation():
    """The fsdp_tp contracts evaluate clean on their OWN 2-D mesh
    (Contract.mesh_spec) with the trainer-derived psum budget — and the
    artifacts really carry it (a zero budget would vacuously pass the new
    rules)."""
    from distributed_pytorch_training_tpu.analysis.contracts import (
        get_contract,
    )
    from distributed_pytorch_training_tpu.analysis.hlo_rules import (
        check_artifacts, evaluate_contract,
    )

    for name in ("fsdp_tp", "fsdp_tp_int8_mh"):
        a = evaluate_contract(get_contract(name))
        assert a.model_shards == 2
        assert a.tp_expected_psums == 4 * DEPTH + 2
        assert a.tp_expected_model_gathers == 0  # the gather-regression pin
        # the CE stats really carry a nonzero floor-aware budget: 4 rows
        # per data shard (2/device x 8 devices / 4 shards) x 15 positions
        # x width 2 — over the contract's 64 floor, so the rule binds at
        # +2 (not vacuously at +0)
        assert a.tp_ce_stat_elements == 2 * 4 * (16 - 1)
        assert a.tp_ce_stat_elements >= a.min_elements
        findings = check_artifacts(a)
        assert not findings, (name, [f.message for f in findings])


def _synthetic_tp_text(model_ars=10, model_gathers=0, data_gathers=5,
                       data_scatters=5, extra=""):
    """Synthetic optimized-HLO text for the mutation tests: 4 batch shards
    x 2 model shards (8 devices, model minor)."""
    model_g = "{{0,1},{2,3},{4,5},{6,7}}"
    data_g = "{{0,2,4,6},{1,3,5,7}}"
    lines = ["HloModule synthetic", "ENTRY main {"]
    for i in range(model_ars):
        lines.append(f"  %ar{i} = f32[4,16,32]{{2,1,0}} all-reduce(%x), "
                     f"replica_groups={model_g}, to_apply=%sum")
    for i in range(model_gathers):
        lines.append(f"  %mg{i} = f32[4,16,64]{{2,1,0}} all-gather(%x), "
                     f"replica_groups={model_g}, dimensions={{2}}")
    for i in range(data_gathers):
        lines.append(f"  %dg{i} = f32[4096]{{0}} all-gather(%x), "
                     f"replica_groups={data_g}, dimensions={{0}}")
    for i in range(data_scatters):
        lines.append(f"  %ds{i} = f32[1024]{{0}} reduce-scatter(%x), "
                     f"replica_groups={data_g}, to_apply=%sum")
    if extra:
        lines.append(extra)
    lines.append("  input_output_alias={ {0}: (0, {}, may-alias) }")
    lines.append("}")
    return "\n".join(lines)


def _tp_artifacts(text, **overrides):
    from distributed_pytorch_training_tpu.analysis.hlo_rules import (
        StepArtifacts,
    )

    kw = dict(name="synthetic", optimized_text=text,
              config={"fsdp_explicit": True}, n_shards=4, model_shards=2,
              tp_expected_psums=10, tp_expected_model_gathers=0,
              min_elements=128,
              layer_group_padded_sizes=(4096, 4096, 4096, 4096, 4096))
    kw.update(overrides)
    return StepArtifacts(**kw)


class TestTpRuleMutations:
    """Each new rule must flag a synthetic violation (the ISSUE-3 mutation
    discipline) — and pass the clean text."""

    def _check(self, text, rule, **overrides):
        from distributed_pytorch_training_tpu.analysis.hlo_rules import (
            check_artifacts,
        )

        return check_artifacts(_tp_artifacts(text, **overrides),
                               rules=[rule])

    def test_clean_text_passes_both_rules(self):
        text = _synthetic_tp_text()
        assert not self._check(text, "tp-psum-signature")
        assert not self._check(text, "fsdp-gather-rides-data-only")

    def test_missing_model_psum_flagged(self):
        f = self._check(_synthetic_tp_text(model_ars=9),
                        "tp-psum-signature")
        assert f and "expected exactly 10" in f[0].message

    def test_extra_model_psum_flagged(self):
        assert self._check(_synthetic_tp_text(model_ars=11),
                           "tp-psum-signature")

    def test_model_gather_regression_flagged(self):
        # the vocab-scale logits gather the parallel-vocab CE removed:
        # its reappearance is the regression the rule pins at zero
        f = self._check(_synthetic_tp_text(model_gathers=1),
                        "tp-psum-signature")
        assert f and "regression it replaced" in f[0].message

    def test_ce_stats_raise_the_psum_budget_when_over_floor(self):
        # with batch-shaped CE stats over the floor the budget is 10+2:
        # 12 psums pass, the bare structural 10 now FAILS (a dropped CE
        # stat collective is a lost loss reduction, not noise)
        assert not self._check(_synthetic_tp_text(model_ars=12),
                               "tp-psum-signature",
                               tp_ce_stat_elements=2048)
        f = self._check(_synthetic_tp_text(model_ars=10),
                        "tp-psum-signature", tp_ce_stat_elements=2048)
        assert f and "expected exactly 12" in f[0].message
        # under the floor the stats are census-invisible: budget stays 10
        assert not self._check(_synthetic_tp_text(model_ars=10),
                               "tp-psum-signature", tp_ce_stat_elements=64)

    def test_missing_budget_is_itself_a_finding(self):
        f = self._check(_synthetic_tp_text(), "tp-psum-signature",
                        tp_expected_psums=0)
        assert f and "without a model-axis collective budget" \
            in f[0].message

    def test_mesh_spanning_gather_flagged(self):
        all_g = "{{0,1,2,3,4,5,6,7}}"
        extra = (f"  %bad = f32[4096]{{0}} all-gather(%x), "
                 f"replica_groups={all_g}, dimensions={{0}}")
        f = self._check(_synthetic_tp_text(extra=extra),
                        "fsdp-gather-rides-data-only")
        assert f and "spanning" in f[0].message

    def test_model_axis_scatter_flagged(self):
        model_g = "{{0,1},{2,3},{4,5},{6,7}}"
        extra = (f"  %bad = f32[1024]{{0}} reduce-scatter(%x), "
                 f"replica_groups={model_g}, to_apply=%sum")
        f = self._check(_synthetic_tp_text(extra=extra),
                        "fsdp-gather-rides-data-only")
        assert f and "MODEL axis" in f[0].message

    def test_rules_abstain_without_model_axis(self):
        # 1-D artifacts never consult the classifier — no relaxation of
        # existing contracts, no accidental binding
        text = _synthetic_tp_text()
        assert not self._check(text, "tp-psum-signature", model_shards=1)
        assert not self._check(text, "fsdp-gather-rides-data-only",
                               model_shards=1)


def test_replica_group_axis_classifier():
    from distributed_pytorch_training_tpu.analysis.hlo_rules import (
        parse_replica_groups, replica_group_axis,
    )

    assert replica_group_axis("{{0,1},{2,3}}", 2, 2) == "model"
    assert replica_group_axis("{{0,2},{1,3}}", 2, 2) == "data"
    assert replica_group_axis("{{0,1,2,3}}", 2, 2) == "all"
    assert replica_group_axis("{{0,3},{1,2}}", 2, 2) == "other"
    assert replica_group_axis("", 2, 2) == "unknown"
    # iota form: [n_groups, size]<=[total] in iota order == consecutive
    assert parse_replica_groups("[2,2]<=[4]") == ((0, 1), (2, 3))
    assert replica_group_axis("[2,2]<=[4]", 2, 2) == "model"
    # transposed iota — XLA's strided-group print form: iota over the
    # reshape dims, transposed, flattened, then chunked
    assert parse_replica_groups("[2,2]<=[2,2]T(1,0)") == ((0, 2), (1, 3))
    assert replica_group_axis("[2,2]<=[2,2]T(1,0)", 2, 2) == "data"
    # malformed perm / mismatched sizes are refused, not guessed
    assert parse_replica_groups("[2,2]<=[2,2]T(0,0)") is None
    assert parse_replica_groups("[2,3]<=[4]") is None


def test_census_extracts_iota_replica_groups_from_hlo_lines():
    """The line regex must capture every groups shape the parser decodes —
    incl. multi-dim iota with a transpose suffix (XLA's strided-group
    print form); a capture miss would classify real data-axis collectives
    as 'unknown' and misfire the TP rules on backends that print it."""
    from distributed_pytorch_training_tpu.analysis.hlo_rules import (
        collective_census, replica_group_axis,
    )

    text = "\n".join([
        "HloModule m",
        "ENTRY main {",
        "  %a = f32[4096]{0} all-gather(%x), "
        "replica_groups=[2,4]<=[4,2]T(1,0), dimensions={0}",
        "  %b = f32[4096]{0} all-reduce(%y), "
        "replica_groups=[4,2]<=[8], to_apply=%sum",
        "}",
    ])
    rows = {r["op"]: r for r in collective_census(text)}
    # [2,4]<=[4,2]T(1,0): iota(8).reshape(4,2).T -> groups {0,2,4,6},{1,3,5,7}
    assert replica_group_axis(rows["all-gather"]["replica_groups"],
                              4, 2) == "data"
    # plain iota [4,2]<=[8]: consecutive pairs == the model groups
    assert replica_group_axis(rows["all-reduce"]["replica_groups"],
                              4, 2) == "model"


# --- wire accounting --------------------------------------------------------


def test_tp_data_axis_bytes_drop_by_1_over_m():
    """The 1/M gather/scatter reduction as accounting: the data-axis
    bytes computed over the TP-LOCAL template are exactly the 1-D
    number / M for every model degree (sizes divisible by every tested
    M*N, so padding cannot smuggle in a dependence) — equivalently, the
    per-element data-axis accounting is model-axis-count independent."""
    tmpl = {"k": jax.ShapeDtypeStruct((64, 24), jnp.float32),
            "b": jax.ShapeDtypeStruct((48,), jnp.float32)}
    sd = {"k": 0, "b": 0}
    base = wire_bytes_for_config(tmpl, dict(fsdp_explicit=True), 2)
    for m in (1, 2, 4):
        local = tp_local_struct(tmpl, sd, m)
        got = wire_bytes_for_config(local, dict(fsdp_explicit=True), 2)
        assert got == base // m, (m, got, base)
    # the TP term adds on top, via the cfg key
    with_tp = wire_bytes_for_config(
        tp_local_struct(tmpl, sd, 2),
        dict(fsdp_explicit=True, tp_psum_bytes=1000), 2)
    assert with_tp == base // 2 + 1000


def test_tp_psum_bytes_per_step_formula():
    b = tp_psum_bytes_per_step(32, 2, 4, 16, 2, tp_vocab=True,
                               padded_vocab=64)
    # the vocab head's wire is the two (B, S, 2) CE stat all-reduces
    # (32 bytes x B x S) — NOT the 4 x B x S x padded_vocab logits
    # gather the parallel-vocab CE replaced
    assert b == 8 * (4 * 16 * 32) * 10 + 32 * 4 * 16
    assert b < 8 * (4 * 16 * 32) * 10 + 4 * 4 * 16 * 64  # strictly shrank
    assert tp_psum_bytes_per_step(32, 2, 4, 16, 1) == 0
    no_vocab = tp_psum_bytes_per_step(32, 2, 4, 16, 2)
    assert no_vocab == 8 * (4 * 16 * 32) * 8


def test_emit_wire_accounting_splits_tp_tier(tmp_path):
    """The telemetry satellite: model-axis psum bytes land in their OWN
    counter row (axis="model") and `telemetry summary` reports them next
    to the data-axis number."""
    import json

    from distributed_pytorch_training_tpu import telemetry
    from distributed_pytorch_training_tpu.parallel.grad_sync import (
        emit_wire_accounting,
    )
    from distributed_pytorch_training_tpu.telemetry.__main__ import (
        main as telemetry_main,
    )

    stream = tmp_path / "t.jsonl"
    telemetry.configure(str(stream), meta={"entry": "test"})
    try:
        params = {"k": np.zeros((64, 24), np.float32)}
        out = emit_wire_accounting(
            params, dict(fsdp_explicit=True, model_shards=2,
                         tp_psum_bytes=4096), 2)
        assert out["tp_psum_bytes_per_replica"] == 4096
        assert out["wire_bytes_per_replica"] == 8 * 64 * 24
    finally:
        telemetry.reset()
    events = [json.loads(ln) for ln in stream.read_text().splitlines()]
    tp_rows = [e for e in events
               if e.get("name") == "tp_psum_bytes_per_replica"]
    assert tp_rows and tp_rows[0]["axis"] == "model"
    data_rows = [e for e in events
                 if e.get("name") == "wire_bytes_per_replica"]
    assert data_rows and data_rows[0]["axis"] == "data"
    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        assert telemetry_main(["summary", str(stream), "--json"]) == 0
    summary = json.loads(buf.getvalue())
    assert summary["wire"]["tp_psum_bytes_per_replica"] == 4096
    assert summary["wire"]["wire_bytes_per_replica"] == 8 * 64 * 24


# --- guards / composition ---------------------------------------------------


def test_tp_requires_a_tp_capable_model(devices):
    from distributed_pytorch_training_tpu.models.resnet import resnet18

    mesh = build_mesh(MeshSpec(data=2, model=2), devices=devices[:4])
    t = Trainer(LanguageModelingTask(), mesh,
                TrainConfig(seed=0, fsdp_explicit=True))
    with pytest.raises(ValueError, match="no explicit-TP form"):
        t.init_state(resnet18(num_classes=10),
                     np.zeros((1, 32, 32, 3), np.float32), sgd(0.1),
                     jax.random.PRNGKey(0))


def test_tp_rejects_indivisible_heads(devices):
    mesh = build_mesh(MeshSpec(data=1, model=4), devices=devices[:4])
    t = Trainer(LanguageModelingTask(), mesh,
                TrainConfig(seed=0, fsdp_explicit=True))
    with pytest.raises(ValueError, match="not divisible"):
        t.init_state(GPT2LMHead(vocab_size=VOCAB, hidden_dim=32, depth=1,
                                num_heads=2, max_position=SEQ),
                     np.zeros((1, SEQ), np.int32), sgd(0.1),
                     jax.random.PRNGKey(0))


def test_tp_rejects_dropout():
    # indivisible vocab keeps the embedding off the vocab-parallel path
    # (no axis_index before the blocks), so the dropout guard inside the
    # first block is what fires — even outside a shard_map
    model = GPT2LMHead(vocab_size=50257, hidden_dim=32, depth=1,
                       num_heads=2, max_position=SEQ, dropout_rate=0.1,
                       tp_size=2, tp_axis=MODEL)
    with pytest.raises(ValueError, match="dropout"):
        jax.eval_shape(
            lambda: model.init(
                {"params": jax.random.PRNGKey(0),
                 "dropout": jax.random.PRNGKey(1)},
                jnp.zeros((2, SEQ), jnp.int32), train=True))


def test_build_lm_trainer_zero1_model_axis_keeps_stock_clip(devices):
    """zero1 on a model-axis mesh (newly reachable through the harness's
    mesh_spec) runs the per-leaf GSPMD update OUTSIDE shard_map — the
    clip must stay stock (shard_axes=None), or its batch-axes psum hits
    unbound axis names at trace (the train.py exclusion, mirrored)."""
    from distributed_pytorch_training_tpu.experiments.harness import (
        build_lm_trainer, synth_token_batch,
    )

    trainer, state, mesh = build_lm_trainer(
        devices[:4], False, "gpt2_124m", SEQ,
        model_kwargs=dict(hidden_dim=32, depth=1, num_heads=2),
        zero1=True, mesh_spec="data=2,model=2")
    assert trainer._zero1_gspmd
    batch, _gb = synth_token_batch(mesh, 2, SEQ)
    _s, m = trainer._train_step(state, batch, jax.random.PRNGKey(0))
    assert np.isfinite(float(m["loss_sum"]))


def test_zero1_tp_wire_rejection_points_at_fsdp_explicit(devices):
    """The carried ROADMAP item, closed: the per-leaf GSPMD zero1 path
    rejects wire compression WITH a pointer to --fsdp-explicit + TP
    (PARITY.md records the path as subsumed)."""
    mesh = build_mesh(MeshSpec(data=4, model=2), devices=devices)
    with pytest.raises(ValueError, match="fsdp-explicit"):
        Trainer(LanguageModelingTask(), mesh,
                TrainConfig(zero1=True, wire_dtype="int8_multihop"),
                rules=GPT2LMHead.partition_rules())


def test_validate_mesh_rejects_model_axis_for_ruleless_models(devices):
    from distributed_pytorch_training_tpu.parallel import validate_mesh

    mesh = build_mesh(MeshSpec(data=2, model=2), devices=devices[:4])
    with pytest.raises(ValueError, match="model"):
        validate_mesh(mesh, rules=None)
    validate_mesh(mesh, rules=GPT2LMHead.partition_rules())  # usable: ok


# --- checkpoint -------------------------------------------------------------


@pytest.mark.slow  # ~12 s; sharded-layout checkpoint roundtrip stays fast via the richer fsdp flat-params+EF leg
def test_tp_checkpoint_roundtrip_bitwise(mesh_tp, tmp_path):
    """The model-major at-rest layout round-trips through the async
    manifest-verified checkpoint path bit-exactly, and the restored run
    continues the trajectory bitwise."""
    from distributed_pytorch_training_tpu.training.checkpoint import (
        CheckpointManager,
    )

    batch = _batch(mesh_tp)
    key = jax.random.PRNGKey(1)
    t, state = _trainer(mesh_tp, "adamw", fsdp=True, wire="int8_multihop")
    state, _ = t._train_step(state, batch, key)

    ckpt = CheckpointManager(str(tmp_path / "ckpt"))
    ckpt.save(1, state, wait=True)

    t2, template = _trainer(mesh_tp, "adamw", fsdp=True,
                            wire="int8_multihop")
    restored, epoch, _sie = ckpt.restore_latest(template)
    ckpt.close()
    assert epoch == 1
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b))),
        (state.params, state.opt_state, state.grad_sync),
        (restored.params, restored.opt_state, restored.grad_sync))
    s_a, m_a = t._train_step(state, batch, key)
    s_b, m_b = t2._train_step(restored, batch, key)
    np.testing.assert_array_equal(np.asarray(m_a["loss_sum"]),
                                  np.asarray(m_b["loss_sum"]))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b))),
        s_a.params, s_b.params)


# --- serving on the 2-D mesh ------------------------------------------------


def test_serving_engine_tp_mesh_matches_1d(devices):
    """`--mesh data=2,model=2` serving: the served weights shard over the
    model axis via the GSPMD rules and the generated greedy tokens match
    the 1-D engine's (multi-chip serving of big models — the ISSUE-13
    motivation's serving half)."""
    from distributed_pytorch_training_tpu.experiments.harness import (
        build_serving_engine,
    )

    overrides = dict(vocab_size=VOCAB, hidden_dim=32, depth=2, num_heads=2)
    prompts = [np.arange(5, dtype=np.int32),
               np.arange(9, dtype=np.int32) % VOCAB]

    def tokens(mesh_spec):
        engine, mesh = build_serving_engine(
            devices[:4], "gpt2_124m", buckets=(16,), rows=4,
            max_new_tokens=4, model_overrides=overrides,
            mesh_spec=mesh_spec)
        if mesh_spec:
            wte = engine._served["wte"]["embedding"]
            assert not wte.sharding.is_fully_replicated
        return [r.tokens.tolist() for r in engine.serve_tokens(prompts)]

    assert tokens("data=2,model=2") == tokens(None)


def test_serving_engine_rejects_model_axis_without_rules(devices):
    from distributed_pytorch_training_tpu.serving.engine import (
        InferenceEngine, ServeConfig,
    )

    mesh = build_mesh(MeshSpec(data=2, model=2), devices=devices[:4])
    model = _tiny_gpt2()
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((1, SEQ), np.int32), train=False)["params"]
    with pytest.raises(ValueError, match="partition rules"):
        InferenceEngine(model, mesh,
                        ServeConfig(buckets=(8,), rows=4,
                                    max_new_tokens=2), params)


# --- ring attention on the TP mesh ------------------------------------------


def test_ring_attention_sharded_inside_tp_mesh_shard_map(devices):
    """`ring_attention_sharded` (the in-shard_map form): called with the
    bound `seq` axis inside a shard_map over a (data, seq, model) mesh —
    the nested-shard_map-free entry the explicit TP step can compose with
    — matches full attention."""
    from jax.sharding import PartitionSpec as P

    from distributed_pytorch_training_tpu.models.layers import (
        dot_product_attention,
    )
    from distributed_pytorch_training_tpu.ops.ring_attention import (
        ring_attention_sharded,
    )
    from distributed_pytorch_training_tpu.parallel.collectives import (
        shard_map,
    )
    from distributed_pytorch_training_tpu.parallel.mesh import SEQ as SEQ_AX

    mesh = build_mesh(MeshSpec(data=2, seq=2, model=2), devices=devices)
    rng = np.random.RandomState(0)
    q = rng.randn(2, 8, 2, 4).astype(np.float32)
    k = rng.randn(2, 8, 2, 4).astype(np.float32)
    v = rng.randn(2, 8, 2, 4).astype(np.float32)
    ref = np.asarray(dot_product_attention(jnp.asarray(q), jnp.asarray(k),
                                           jnp.asarray(v)))

    spec = P(BATCH_AXES, SEQ_AX, MODEL, None)
    f = shard_map(
        lambda a, b, c: ring_attention_sharded(a, b, c, axis_name=SEQ_AX,
                                               causal=False,
                                               use_pallas=False),
        mesh, in_specs=(spec, spec, spec), out_specs=spec)
    out = np.asarray(jax.jit(f)(q, k, v))
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-6)
