"""Test harness: an 8-device virtual CPU mesh.

This is the TPU-world "fake backend" the reference lacks (SURVEY.md §4): real
collectives on 8 XLA CPU devices, no cluster needed. Must run before jax is
imported anywhere, hence the env mutation at module import time.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the image pre-sets JAX_PLATFORMS=axon (real TPU)
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
import pytest  # noqa: E402

# The image's sitecustomize imports jax before this conftest runs, so jax's
# config has already captured JAX_PLATFORMS=axon — override via the config API.
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # Older jax has no jax_num_cpu_devices option; the XLA_FLAGS fallback
    # above provides the 8 virtual devices there.
    pass


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture(scope="session")
def mesh8(devices):
    from distributed_pytorch_training_tpu.parallel import MeshSpec, build_mesh

    return build_mesh(MeshSpec(data=8), devices=devices)
