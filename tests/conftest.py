"""Test harness: an 8-device virtual CPU mesh.

This is the TPU-world "fake backend" the reference lacks (SURVEY.md §4): real
collectives on 8 XLA CPU devices, no cluster needed. Must run before jax is
imported anywhere, hence the env mutation at module import time.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the image pre-sets JAX_PLATFORMS=axon (real TPU)
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
import pytest  # noqa: E402

# The image's sitecustomize imports jax before this conftest runs, so jax's
# config has already captured JAX_PLATFORMS=axon — override via the config API.
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # Older jax has no jax_num_cpu_devices option; the XLA_FLAGS fallback
    # above provides the 8 virtual devices there.
    pass


# ---------------------------------------------------------------------------
# Per-file wall budget for the resilience/elastic/fleet chaos suites
# (ISSUE 12 satellite). These files host subprocess + multi-restart
# harnesses whose cost grows a leg at a time; without a stated budget a
# new chaos leg can silently push the fast suite into the 870 s tier-1
# timeout and the failure shows up as a global timeout, not a named
# culprit. Budgets bind only on FAST runs (`-m 'not slow'`, the tier-1
# invocation) and hold ~3x headroom over measured cost; the slow chaos
# legs are budgeted by the marker instead. DPT_TEST_FILE_BUDGET_OFF=1
# disables enforcement (the report still prints).
# ---------------------------------------------------------------------------

_FILE_BUDGETS_S = {
    "test_resilience.py": 300.0,   # measured ~95 s fast
    "test_elastic.py": 240.0,      # measured ~75 s fast
    "test_fleet.py": 60.0,         # stub children: measured ~1 s fast
    # The 2-D TP x FSDP parity suite (ISSUE 13): every leg compiles a
    # fresh shard_map step over the 4-device 2-D mesh — per-leg compile
    # cost is the budget driver, and a new parity leg silently pushing
    # the fast suite into the 870 s tier-1 timeout must name itself here.
    "test_tp.py": 300.0,           # measured ~100 s fast
    # The fleet observability suite (ISSUE 14): synthetic streams + one
    # real mock-step loop leg + HTTP scrapes with sub-second sleeps —
    # cheap today, but endpoint tests accrete timeouts easily.
    "test_telemetry_fleet.py": 90.0,   # measured ~3 s fast
    # The device-time attribution suite (ISSUE 15): real jax.profiler
    # captures through the instrumented loop + HTTP endpoints — trace
    # capture/parse cost accretes per leg, so new windows name
    # themselves here.
    "test_device_profile.py": 120.0,   # measured ~7 s fast
    # The two-tier hier wire suite (ISSUE 16): every parity leg compiles
    # a fresh shard_map step over the (slice=2, data=4) mesh, plus one
    # contract evaluation — per-leg compile cost is the budget driver.
    "test_hier.py": 150.0,             # measured ~39 s fast
    # The continuous-batching suite (ISSUE 17): four SlotEngine warmups
    # (fp32 + int8 on the 8-way mesh, two fleet replicas on 4-device
    # slices), one contract evaluation, and one jitted fixed-pad
    # reference forward for the bitwise pins — compile count is the
    # budget driver, so a new engine config or bucket rung must name
    # itself here.
    "test_continuous.py": 150.0,       # measured ~33 s fast
    # The concurrency-discipline suite (ISSUE 18): AST lint over tmp
    # sources + tiny stub engines + deterministic gated interleavings
    # with sub-second waits — the budget driver is the sum of the small
    # join timeouts, which accrete per interleaving test.
    "test_analysis_concurrency.py": 60.0,   # measured ~7 s fast
    # The speculative-decoding suite (ISSUE 19): one SpeculativeEngine
    # warmup (draft prefill + propose + verify per bucket) plus a plain
    # SlotEngine warmup for the bitwise cross-pins, an oracle-draft
    # engine, and one contract evaluation — warmup compile count is the
    # budget driver, so a new engine or bucket rung names itself here.
    "test_speculative.py": 180.0,      # measured ~48 s fast
    # The control-plane suite (ISSUE 20): the autopilot chaos leg runs a
    # full supervised train with an injected persistent straggler, one
    # boundary shrink, one capacity-return grow, and the bitwise parity
    # continuation — three elastic recompiles plus ~0.9 s x 3 of
    # injected stall dominate; the policy/probe/gate unit legs are
    # milliseconds.
    "test_control.py": 240.0,          # measured ~49 s fast
}
_file_seconds: dict = {}


def pytest_runtest_logreport(report):
    fname = report.nodeid.split("::", 1)[0].rsplit("/", 1)[-1]
    if fname in _FILE_BUDGETS_S:
        _file_seconds[fname] = (_file_seconds.get(fname, 0.0)
                                + report.duration)


def _budget_enforced(config) -> bool:
    if os.environ.get("DPT_TEST_FILE_BUDGET_OFF"):
        return False
    return "not slow" in (config.getoption("-m") or "")


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    if not _file_seconds:
        return
    terminalreporter.write_sep("-", "chaos-suite wall budget")
    enforced = _budget_enforced(config)
    for fname, secs in sorted(_file_seconds.items()):
        budget = _FILE_BUDGETS_S[fname]
        if enforced:
            verdict = "OVER BUDGET" if secs > budget else "ok"
            terminalreporter.write_line(
                f"{fname}: {secs:.1f}s / {budget:.0f}s budget ({verdict})")
        else:  # slow legs run here — the fast budget does not apply
            terminalreporter.write_line(
                f"{fname}: {secs:.1f}s (fast-suite budget {budget:.0f}s "
                "not enforced on this run)")


@pytest.hookimpl(trylast=True)
def pytest_sessionfinish(session, exitstatus):
    global _final_exitstatus
    if _budget_enforced(session.config):
        over = {f: s for f, s in _file_seconds.items()
                if s > _FILE_BUDGETS_S[f]}
        if over and session.exitstatus == 0:
            for fname, secs in over.items():
                print(f"BUDGET: {fname} took {secs:.1f}s, over its "
                      f"{_FILE_BUDGETS_S[fname]:.0f}s fast-suite budget "
                      "— a chaos leg grew past the tier-1 allowance; "
                      "mark it slow or shrink it", flush=True)
            session.exitstatus = 1
    _final_exitstatus = int(session.exitstatus)


_final_exitstatus = None


@pytest.hookimpl(trylast=True)
def pytest_unconfigure(config):
    """Skip interpreter teardown once the run is reported.

    A full fast-suite run leaves hundreds of compiled XLA executables
    and device buffers behind; their destructors cost ~8-10 s of wall
    AFTER the final summary prints — time that counts against the 870 s
    tier-1 timeout and buys zero coverage. The summary and the final
    exit status are settled by this point (terminal reporting is a
    sessionfinish hookwrapper, unconfigure runs after it), so leave via
    os._exit. DPT_NO_FAST_EXIT=1 restores the normal shutdown (atexit
    consumers, debugging); coverage runs keep it automatically."""
    import sys
    if _final_exitstatus is None or os.environ.get("DPT_NO_FAST_EXIT"):
        return
    if config.pluginmanager.hasplugin("_cov"):
        return
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(_final_exitstatus)


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture(scope="session")
def mesh8(devices):
    from distributed_pytorch_training_tpu.parallel import MeshSpec, build_mesh

    return build_mesh(MeshSpec(data=8), devices=devices)
