"""Explicit full-parameter FSDP (training/loop.py `fsdp_explicit`).

The contract (ISSUE 7 acceptance): on the same data-parallel mesh the
explicit-FSDP step must (a) train the SAME trajectory as the replicated
DDP-style update at reassociation tolerance in fp32 — 20 steps, grad-accum
on and off — the layout (flat-sharded at rest + just-in-time per-layer
gathers) is a performance fact, not a math fact; (b) really hold params AND
moments flat-sharded 1/N per replica at rest (the memory division the mode
exists for); (c) carry exactly one param all-gather per layer group and one
gradient reduce-scatter per layer group in the compiled HLO, with NO
gradient-sized all-reduce (the per-layer census, floor-aware like the
analysis/ rules); and (d) round-trip flat-sharded params + EF residuals
through the async manifest-verified checkpoint path bit-exactly.

The int8_multihop wire compresses BOTH directions (s8 gradient scatter with
error feedback + s8 param gathers); its contract is bounded drift +
convergence, not fp32 parity (PARITY.md states the error model).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_training_tpu.models.gpt2 import GPT2LMHead
from distributed_pytorch_training_tpu.parallel import (
    MeshSpec, build_mesh, shard_batch,
)
from distributed_pytorch_training_tpu.parallel.grad_sync import (
    build_layer_plan, fsdp_gather_bytes, wire_bytes_for_config,
)
from distributed_pytorch_training_tpu.training import TrainConfig, Trainer
from distributed_pytorch_training_tpu.training.optim import adamw, sgd
from distributed_pytorch_training_tpu.training.tasks import LanguageModelingTask

SEQ = 16
VOCAB = 64
DP_AXES = ("data", "fsdp")


def _tiny_gpt2():
    return GPT2LMHead(vocab_size=VOCAB, hidden_dim=32, depth=2, num_heads=2,
                      max_position=SEQ)


def _make_tx(name, shard_axes=None):
    if name == "sgd":
        return sgd(0.1, momentum=0.9, weight_decay=5e-4)
    # clip active so the psum'd global-norm path runs on the shards
    return adamw(1e-2, grad_clip_norm=1.0, shard_axes=shard_axes)


def _trainer(mesh, opt, fsdp, wire="fp32", grad_accum=1):
    t = Trainer(LanguageModelingTask(compute_dtype=jnp.float32), mesh,
                TrainConfig(seed=0, fsdp_explicit=fsdp, wire_dtype=wire,
                            grad_accum=grad_accum))
    # the sharded update (fsdp's, like zero1's) needs the psum-aware clip;
    # the replicated path must NOT carry shard axes (unbound-name trace
    # error on the non-shard_map path)
    tx = _make_tx(opt, shard_axes=DP_AXES if (fsdp and t._fsdp) else None)
    state = t.init_state(_tiny_gpt2(), np.zeros((1, SEQ), np.int32), tx,
                         jax.random.PRNGKey(0))
    return t, state


def _batch(mesh, n=16):
    rng = np.random.RandomState(0)
    return shard_batch({
        "input_ids": rng.randint(0, VOCAB, (n, SEQ)).astype(np.int32),
        "weight": np.ones(n, np.float32),
    }, mesh)


def _run(mesh, opt, fsdp, steps=20, wire="fp32", grad_accum=1):
    batch = _batch(mesh)
    key = jax.random.PRNGKey(1)
    t, s = _trainer(mesh, opt, fsdp, wire=wire, grad_accum=grad_accum)
    losses = []
    for _ in range(steps):
        s, m = t._train_step(s, batch, key)
        losses.append(float(m["loss_sum"]) / max(float(m["weight"]), 1.0))
    return losses, s, t


def _full_params(t, s):
    """Model-shaped params from either layout."""
    return t._fsdp_unflatten(s.params) if t._fsdp else s.params


def _assert_params_close(ref_params, params, **tol):
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_allclose(
            np.asarray(jax.device_get(x)), np.asarray(jax.device_get(y)),
            **tol),
        ref_params, params)


# --- fp32 parity vs the replicated path ------------------------------------


@pytest.mark.slow  # ~7 s; the adamw 20-step leg stays fast and is the stricter parity
def test_fsdp_sgd_20step_matches_replicated(mesh8):
    l_rep, s_rep, t_rep = _run(mesh8, "sgd", fsdp=False)
    l_fs, s_fs, t_fs = _run(mesh8, "sgd", fsdp=True)
    np.testing.assert_allclose(l_rep, l_fs, rtol=2e-5)
    _assert_params_close(_full_params(t_rep, s_rep),
                         _full_params(t_fs, s_fs), rtol=1e-4, atol=1e-6)
    assert l_rep[-1] < l_rep[0]


def test_fsdp_adamw_matches_replicated(mesh8):
    """AdamW + active global-norm clip: the psum-aware clip must see the
    same global norm from 1/N shards as the replicated path sees from full
    gradients (test_zero1's tolerance rationale applies verbatim)."""
    l_rep, s_rep, t_rep = _run(mesh8, "adamw", fsdp=False, steps=6)
    l_fs, s_fs, t_fs = _run(mesh8, "adamw", fsdp=True, steps=6)
    np.testing.assert_allclose(l_rep, l_fs, rtol=2e-5)
    _assert_params_close(_full_params(t_rep, s_rep),
                         _full_params(t_fs, s_fs), rtol=2e-2, atol=2e-3)


@pytest.mark.slow  # ~8 s; the adamw non-accum parity stays fast and the accum lowering is gated by the fsdp_accum matrix contract
def test_fsdp_grad_accum_20step_matches_replicated_grad_accum(mesh8):
    """grad_accum=2: the scan carry holds per-leaf gradient SHARDS and
    each microbatch's per-layer scatter runs inside the scan body; the
    trajectory must still match the replicated accum path."""
    l_rep, s_rep, t_rep = _run(mesh8, "sgd", fsdp=False, grad_accum=2)
    l_fs, s_fs, t_fs = _run(mesh8, "sgd", fsdp=True, grad_accum=2)
    np.testing.assert_allclose(l_rep, l_fs, rtol=2e-5)
    _assert_params_close(_full_params(t_rep, s_rep),
                         _full_params(t_fs, s_fs), rtol=1e-4, atol=1e-6)


@pytest.mark.slow  # ~10 s convergence smoke; EF exactness stays fast via the flat-params+EF checkpoint roundtrip and the fsdp_int8_mh matrix contract
def test_fsdp_int8_multihop_converges_with_bounded_drift(mesh8):
    """The fully compressed wire (s8 scatter + EF, s8 param gathers): NOT
    an exactness mode — the contract is convergence and bounded drift from
    the fp32 trajectory (PARITY.md)."""
    l_fp32, _, _ = _run(mesh8, "sgd", fsdp=True, steps=8)
    l_mh, s_mh, t_mh = _run(mesh8, "sgd", fsdp=True, steps=8,
                            wire="int8_multihop")
    assert l_mh[-1] < l_mh[0]
    np.testing.assert_allclose(l_fp32, l_mh, rtol=2e-2)
    # EF residuals exist per layer group and were actually updated
    plan = t_mh._fsdp_plan
    assert set(s_mh.grad_sync["ef"].keys()) == {g.name for g in plan.groups}
    total = sum(float(jnp.abs(r).sum())
                for r in jax.tree_util.tree_leaves(s_mh.grad_sync["ef"]))
    assert total > 0.0  # int8 quantization always drops something


# --- at-rest layout --------------------------------------------------------


def test_fsdp_params_and_moments_flat_sharded_at_rest(mesh8):
    """The memory win must be real: every parameter AND every AdamW moment
    lives as a 1-D flat-padded chunk of 1/8 the padded size per device —
    not a replicated copy with a sharded-looking spec."""
    t, state = _trainer(mesh8, "adamw", fsdp=True)
    template = t._fsdp_template
    n_checked = 0
    for tree in (state.params, state.opt_state[1].mu, state.opt_state[1].nu):
        for path, leaf in jax.tree_util.tree_leaves_with_path(tree):
            tmpl = template
            for k in path:
                tmpl = tmpl[k.key]
            size = int(np.prod(tmpl.shape) or 1)
            padded = size + (-size % 8)
            assert leaf.ndim == 1 and leaf.shape == (padded,), (
                path, leaf.shape)
            assert not leaf.sharding.is_fully_replicated, path
            shard = leaf.addressable_shards[0].data
            assert shard.shape == (padded // 8,), (path, shard.shape)
            n_checked += 1
    assert n_checked >= 30


def test_fsdp_eval_step_runs_on_unflattened_params(mesh8):
    """Eval takes the at-rest shards and rebuilds model shapes outside
    shard_map (GSPMD inserts the gathers there)."""
    t, state = _trainer(mesh8, "sgd", fsdp=True)
    m = t._eval_step(state, _batch(mesh8))
    assert np.isfinite(float(m["loss_sum"]))


# --- per-layer collective census -------------------------------------------


def _floor_aware_expected(plan, n, floor, wire):
    """Mirror of the analysis/ fsdp rules' expectation arithmetic."""
    sizes = [n * g.row_size for g in plan.groups]
    gathers = sum(1 for s in sizes if s >= floor)
    if wire in ("int8", "int8_multihop"):
        scatters = gathers  # the s8 all-to-all carries the full group
    else:
        scatters = sum(1 for s in sizes if s // n >= floor)
    return gathers, scatters


@pytest.mark.parametrize("wire", [
    "fp32",
    # ~4 s; strictly redundant with the fsdp_int8_mh contract in the
    # matrix gate — the fp32 arm keeps the census shape pinned fast
    pytest.param("int8_multihop", marks=pytest.mark.slow),
])
def test_fsdp_census_one_gather_and_one_scatter_per_layer_group(mesh8, wire):
    """The acceptance census: gathers == layer groups (above the floor),
    gradients land as per-layer reduce-scatter / s8 all-to-all, and NO
    gradient-sized all-reduce survives."""
    from distributed_pytorch_training_tpu.experiments.trace_analysis import (
        grad_sync_census,
    )

    floor = 64
    t, s = _trainer(mesh8, "sgd", fsdp=True, wire=wire)
    text = t._train_step.lower(
        s, _batch(mesh8), jax.random.PRNGKey(1)).compile().as_text()
    census = grad_sync_census(text, min_elements=floor)
    by_op = census["by_op"]

    plan = build_layer_plan(
        jax.tree_util.tree_map(lambda x: np.zeros(x.shape), t._fsdp_template),
        8)
    assert len(plan.groups) == 5  # wte, wpe, block0, block1, ln_f
    exp_gathers, exp_scatters = _floor_aware_expected(plan, 8, floor, wire)
    assert exp_gathers >= 4  # the floor must not trivialize the census

    assert by_op.get("all-gather", 0) == exp_gathers, by_op
    scatters = by_op.get("reduce-scatter", 0) + by_op.get("all-to-all", 0)
    assert scatters == exp_scatters, by_op
    assert by_op.get("all-reduce", 0) == 0, by_op


@pytest.mark.slow  # ~7 s; strictly redundant with the full contract-matrix gate in test_analysis_cli
def test_fsdp_analysis_contracts_pass_without_relaxation(mesh8):
    """The fsdp and fsdp_int8_mh contracts evaluate clean on the live
    trainer — per-layer gather bound, scatter signature, and
    no-full-param-residency all from the real LayerPlan budget (fsdp_accum
    rides the full-matrix `check --json` gate in test_analysis_cli, not
    re-lowered here)."""
    from distributed_pytorch_training_tpu.analysis.contracts import (
        get_contract,
    )
    from distributed_pytorch_training_tpu.analysis.hlo_rules import (
        check_artifacts, evaluate_contract,
    )

    for name in ("fsdp", "fsdp_int8_mh"):
        artifacts = evaluate_contract(get_contract(name), mesh=mesh8)
        assert artifacts.layer_group_padded_sizes  # the budget rode along
        findings = check_artifacts(artifacts)
        assert not findings, (name, [f.message for f in findings])


# --- checkpoint ------------------------------------------------------------


def test_fsdp_checkpoint_roundtrip_flat_params_and_ef(mesh8, tmp_path):
    """Save/restore through the async manifest-verified path: flat-sharded
    params, flat-sharded moments and per-group EF residuals all round-trip
    bit-exactly, keep their dp sharding, and the restored run continues
    the trajectory bitwise."""
    from distributed_pytorch_training_tpu.training.checkpoint import (
        CheckpointManager,
    )

    batch = _batch(mesh8)
    key = jax.random.PRNGKey(1)
    t, state = _trainer(mesh8, "adamw", fsdp=True, wire="int8_multihop")
    state, _ = t._train_step(state, batch, key)

    ckpt = CheckpointManager(str(tmp_path / "ckpt"))  # async default
    ckpt.save(1, state, wait=True)
    assert (tmp_path / "ckpt" / ".manifests").exists()  # verified path

    t2, template = _trainer(mesh8, "adamw", fsdp=True, wire="int8_multihop")
    restored, epoch, step_in_epoch = ckpt.restore_latest(template)
    ckpt.close()
    assert epoch == 1 and step_in_epoch == 0
    assert int(restored.step) == 1

    wte = restored.params["wte"]["embedding"]
    assert wte.ndim == 1 and not wte.sharding.is_fully_replicated
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b))),
        (state.params, state.opt_state, state.grad_sync),
        (restored.params, restored.opt_state, restored.grad_sync))

    s_a, m_a = t._train_step(state, batch, key)
    s_b, m_b = t2._train_step(restored, batch, key)
    np.testing.assert_array_equal(np.asarray(m_a["loss_sum"]),
                                  np.asarray(m_b["loss_sum"]))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b))),
        s_a.params, s_b.params)


# --- mode composition / guards ---------------------------------------------


def test_fsdp_single_shard_is_replicated_passthrough(devices):
    mesh1 = build_mesh(MeshSpec(data=1), devices=devices[:1])
    t, s = _trainer(mesh1, "sgd", fsdp=True)
    assert not t._fsdp  # identity passthrough engaged
    # passthrough state is the ordinary replicated layout
    assert s.params["wte"]["embedding"].ndim == 2
    s, m = t._train_step(s, _batch(mesh1, n=4), jax.random.PRNGKey(1))
    assert np.isfinite(float(m["loss_sum"]))


def test_fsdp_rejects_zero1_and_bucket_cap(mesh8):
    task = LanguageModelingTask(compute_dtype=jnp.float32)
    with pytest.raises(ValueError, match="zero1"):
        Trainer(task, mesh8, TrainConfig(fsdp_explicit=True, zero1=True))
    with pytest.raises(ValueError, match="bucket_cap_mb"):
        Trainer(task, mesh8,
                TrainConfig(fsdp_explicit=True, bucket_cap_mb=25.0))


def test_fsdp_rejects_param_sharding_rules(devices):
    """GSPMD partition rules that shard params over an engaged batch axis
    + fsdp_explicit would silently drop the rules (init_state ignores them
    in fsdp mode) — rejected loudly instead (PARITY.md composition
    matrix). Rules whose batch axes are size-1 on this mesh are fine: they
    shard nothing."""
    mesh_fsdp = build_mesh(MeshSpec(data=2, fsdp=4), devices=devices)
    with pytest.raises(ValueError, match="fsdp_explicit owns"):
        Trainer(LanguageModelingTask(), mesh_fsdp,
                TrainConfig(fsdp_explicit=True),
                rules=GPT2LMHead.partition_rules())
    # pure-DP mesh: the same rules are inert (fsdp axis size 1) — accepted
    mesh_dp = build_mesh(MeshSpec(data=8), devices=devices)
    Trainer(LanguageModelingTask(), mesh_dp,
            TrainConfig(fsdp_explicit=True),
            rules=GPT2LMHead.partition_rules())


# --- wire accounting -------------------------------------------------------


def test_fsdp_gather_bytes_accounting():
    """The `fsdp_gather_bytes` term (ISSUE 7 satellite): exact fp32
    gathers cost ~4 B/element; the s8 multihop gathers ~1 B/element — and
    the per-replica number is independent of the shard count (sizes
    divisible by every tested n, so padding cannot smuggle in a
    dependence)."""
    params = {"a": np.zeros((64, 24), np.float32),
              "b": np.zeros((48,), np.float32)}
    total = 64 * 24 + 48
    for n in (2, 4, 8):
        assert fsdp_gather_bytes(params, "fp32", n) == 4 * total
        assert fsdp_gather_bytes(params, "int8_multihop", n) == total
    assert fsdp_gather_bytes(params, "fp32", 1) == 0  # passthrough
    with pytest.raises(ValueError, match="wire dtype"):
        fsdp_gather_bytes(params, "fp16", 4)


def test_fsdp_wire_bytes_for_config_is_scatter_plus_gather():
    """wire_bytes_for_config under fsdp = scatter bytes at the wire dtype
    plus the gather term — int8_multihop lands at ~2 B/element total, at
    any n (the multihop gradient wire's n-independence argument, now for
    both directions)."""
    params = {"a": np.zeros((64, 24), np.float32),
              "b": np.zeros((48,), np.float32)}
    total = 64 * 24 + 48
    for n in (2, 4, 8):
        assert wire_bytes_for_config(
            params, dict(fsdp_explicit=True), n) == 8 * total
        assert wire_bytes_for_config(
            params, dict(fsdp_explicit=True, wire_dtype="bf16"),
            n) == 6 * total
        assert wire_bytes_for_config(
            params, dict(fsdp_explicit=True, wire_dtype="int8_multihop"),
            n) == 2 * total
    assert wire_bytes_for_config(params, dict(fsdp_explicit=True), 1) == 0
