"""serving/ — manifest-verified batched inference engine (ISSUE 10).

Pins, in order:
* the cache-aware GPT-2 forward leaves the no-cache training path
  BYTE-IDENTICAL HLO (lowering test against a pre-cache reference copy);
* prefill+decode logits match the full-context forward BITWISE in fp32,
  including mixed-length batches vs solo forwards;
* fp32 served logits are bitwise the (compiled, sharded) eval forward —
  the acceptance criterion;
* zero recompiles across >= 20 mixed-length requests within the bucket
  ladder (the compile-count census);
* int8 weight serving reuses the wire-codec grid (bound + grid match);
* the request queue / continuous batcher / drain semantics;
* the serving decode HLO contract + the two new analysis rules
  (mutation-tested, per the checker's own standard);
* `measure_serving` (the bench row) and the slow CLI e2e.
"""

import json
import subprocess
import sys
import textwrap
import threading
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_training_tpu.data.pack import pack_token_rows
from distributed_pytorch_training_tpu.models.gpt2 import GPT2LMHead
from distributed_pytorch_training_tpu.parallel.sharding import shard_batch
from distributed_pytorch_training_tpu.serving import (
    InferenceEngine, QuantizedLeaf, RequestQueue, ServeConfig,
    dequantize_params, drain, int8_weight_bytes, quantize_params,
    serve_forever,
)

VOCAB = 97


def tiny_model(**kw):
    cfg = dict(vocab_size=VOCAB, hidden_dim=32, depth=2, num_heads=2,
               max_position=64)
    cfg.update(kw)
    return GPT2LMHead(**cfg)


@pytest.fixture(scope="module")
def tiny(mesh8):
    model = tiny_model()
    params = model.init(jax.random.PRNGKey(0), np.zeros((1, 8), np.int32),
                        train=False)["params"]
    return model, params


@pytest.fixture(scope="module")
def engine(mesh8, tiny):
    model, params = tiny
    eng = InferenceEngine(
        model, mesh8,
        ServeConfig(buckets=(8, 16), rows=8, max_new_tokens=4), params)
    eng.warmup()
    return eng


def prompts(ns, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, VOCAB, n).astype(np.int32) for n in ns]


# ---------------------------------------------------------------------------
# The cache-aware forward: HLO identity + bitwise logit parity
# ---------------------------------------------------------------------------


class TestCacheForward:
    def test_no_cache_lowering_byte_identical(self, tiny):
        """The cache plumbing contributes ZERO ops when off: lowering the
        new module's no-cache forward is byte-identical to a verbatim copy
        of the PRE-CACHE module (same submodule names, so the texts align
        exactly — flax does not leak class names into HLO)."""
        import functools

        import flax.linen as nn

        from distributed_pytorch_training_tpu.models.layers import (
            MlpBlock, causal_mask, dot_product_attention,
            mask_vocab_padding,
        )

        class RefMHA(nn.Module):  # the pre-cache MultiHeadAttention
            num_heads: int
            head_dim: int

            @nn.compact
            def __call__(self, x, mask=None, deterministic=True):
                dense = functools.partial(nn.DenseGeneral,
                                          dtype=jnp.float32,
                                          param_dtype=jnp.float32,
                                          use_bias=True)
                qkv = dense(features=(3, self.num_heads, self.head_dim),
                            name="qkv")(x)
                q, k, v = (qkv[..., 0, :, :], qkv[..., 1, :, :],
                           qkv[..., 2, :, :])
                y = dot_product_attention(q, k, v, mask=mask,
                                          dtype=jnp.float32)
                return nn.DenseGeneral(features=x.shape[-1], axis=(-2, -1),
                                       dtype=jnp.float32,
                                       param_dtype=jnp.float32,
                                       use_bias=True, name="out")(y)

        class RefBlock(nn.Module):  # the pre-cache TransformerBlock
            num_heads: int
            head_dim: int
            mlp_dim: int

            @nn.compact
            def __call__(self, x, mask=None, deterministic=True):
                ln = functools.partial(nn.LayerNorm, epsilon=1e-5,
                                       dtype=jnp.float32,
                                       param_dtype=jnp.float32)
                y = ln(name="ln1")(x)
                y = RefMHA(num_heads=self.num_heads,
                           head_dim=self.head_dim, name="attn")(
                    y, mask=mask, deterministic=deterministic)
                x = x + y
                y = ln(name="ln2")(x)
                y = MlpBlock(hidden_dim=self.mlp_dim, dtype=jnp.float32,
                             param_dtype=jnp.float32, name="mlp",
                             )(y, deterministic=deterministic)
                return x + y

        class RefGPT2(nn.Module):  # the pre-cache GPT2LMHead.__call__
            @nn.compact
            def __call__(self, input_ids, train=False):
                b, s = input_ids.shape
                wte = nn.Embed(VOCAB, 32, dtype=jnp.float32,
                               param_dtype=jnp.float32,
                               embedding_init=nn.initializers.normal(
                                   stddev=0.02), name="wte")
                x = wte(input_ids)
                pos_ids = jnp.arange(s)[None, :]
                x = x + nn.Embed(64, 32, dtype=jnp.float32,
                                 param_dtype=jnp.float32,
                                 embedding_init=nn.initializers.normal(
                                     stddev=0.01), name="wpe")(pos_ids)
                mask = causal_mask(s)
                for i in range(2):
                    x = RefBlock(num_heads=2, head_dim=16, mlp_dim=128,
                                 name=f"block{i}")(x, mask=mask,
                                                   deterministic=not train)
                x = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32,
                                 param_dtype=jnp.float32, name="ln_f")(x)
                logits = wte.attend(x)
                return mask_vocab_padding(logits.astype(jnp.float32),
                                          VOCAB)

        model, params = tiny
        ids = np.zeros((4, 8), np.int32)
        new_text = jax.jit(
            lambda p, i: model.apply({"params": p}, i, train=False)
        ).lower(params, ids).as_text()
        ref_text = jax.jit(
            lambda p, i: RefGPT2().apply({"params": p}, i, train=False)
        ).lower(params, ids).as_text()
        assert new_text == ref_text

    def test_prefill_is_eval_forward_bitwise(self, tiny):
        model, params = tiny
        rng = np.random.RandomState(1)
        ids = rng.randint(0, VOCAB, (3, 12)).astype(np.int32)
        ev = model.apply({"params": params}, ids, train=False)
        cache0 = model.init_cache(3, 16)
        pre, _cache = model.apply({"params": params}, ids, train=False,
                                  cache=cache0)
        assert bool(jnp.all(pre == ev))

    def test_prefill_decode_matches_full_forward_bitwise(self, tiny):
        """The satellite pin: prefill over the prompt + K forced decode
        steps reproduce the full-context forward's logits BITWISE in
        fp32."""
        model, params = tiny
        rng = np.random.RandomState(2)
        B, S, K = 3, 12, 4
        ids = rng.randint(0, VOCAB, (B, S + K)).astype(np.int32)
        full = model.apply({"params": params}, ids, train=False)
        cache = model.init_cache(B, S + K)
        pre, cache = model.apply({"params": params}, ids[:, :S],
                                 train=False, cache=cache)
        assert bool(jnp.all(pre == full[:, :S]))
        dec = []
        for k in range(K):
            pos = jnp.full((B,), S + k, jnp.int32)
            lg, cache = model.apply({"params": params},
                                    ids[:, S + k][:, None], train=False,
                                    cache=cache, cache_positions=pos)
            dec.append(lg[:, 0])
        assert bool(jnp.all(jnp.stack(dec, axis=1) == full[:, S:]))

    def test_mixed_length_decode_matches_solo_forward_bitwise(self, tiny):
        """Rows at DIFFERENT prompt lengths decode in one batch; each
        row's logits equal its own solo full-context forward bitwise —
        padding and batch company are invisible."""
        model, params = tiny
        rng = np.random.RandomState(3)
        B, S = 3, 12
        lens = [5, 12, 9]
        toks = rng.randint(0, VOCAB, (B, S + 2)).astype(np.int32)
        ids = np.zeros((B, S), np.int32)
        for i, n in enumerate(lens):
            ids[i, :n] = toks[i, :n]
        cache = model.init_cache(B, S + 4)
        pre, cache = model.apply({"params": params}, ids, train=False,
                                 cache=cache)
        pos = jnp.asarray(lens, jnp.int32)
        nxt = jnp.asarray([toks[i, lens[i]] for i in range(B)],
                          jnp.int32)[:, None]
        lg, cache = model.apply({"params": params}, nxt, train=False,
                                cache=cache, cache_positions=pos)
        for i, n in enumerate(lens):
            solo = model.apply({"params": params}, toks[i:i + 1, :n + 1],
                               train=False)
            assert bool(jnp.all(pre[i, :n] == solo[0, :n])), f"row {i}"
            assert bool(jnp.all(lg[i, 0] == solo[0, n])), f"row {i} decode"

    def test_kernel_attention_with_cache_raises(self):
        def fake_kernel(q, k, v, mask=None, dtype=jnp.float32):
            return q

        model = tiny_model(attention_fn=fake_kernel)
        params = model.init(jax.random.PRNGKey(0),
                            np.zeros((1, 8), np.int32),
                            train=False)["params"]
        with pytest.raises(ValueError, match="XLA attention path"):
            model.apply({"params": params}, np.zeros((1, 8), np.int32),
                        train=False, cache=model.init_cache(1, 12))


# ---------------------------------------------------------------------------
# The engine: acceptance pins
# ---------------------------------------------------------------------------


class TestEngine:
    def test_served_logits_bitwise_eval_forward(self, mesh8, tiny, engine):
        """ACCEPTANCE: fp32 served logits == the compiled, sharded eval
        forward, bitwise, for the same (padded) inputs."""
        model, params = tiny
        seqs = prompts((3, 8, 5))
        ids, lengths, _ = pack_token_rows(seqs, 8, engine.config.rows)
        ev = jax.jit(
            lambda p, i: model.apply({"params": p}, i, train=False)
        )(engine._served, shard_batch(ids, mesh8))
        ev = np.asarray(ev)
        for i, res in enumerate(engine.serve_tokens(
                seqs, return_prompt_logits=True)):
            L = len(seqs[i])
            assert res.prompt_logits.shape == (L, VOCAB)
            assert (res.prompt_logits == ev[i, :L]).all(), f"request {i}"
            np.testing.assert_array_equal(res.last_logits, ev[i, L - 1])

    def test_zero_recompiles_across_20_mixed_requests(self, engine):
        """ACCEPTANCE: >= 20 mixed-length requests inside the bucket
        ladder reuse the warmup executables — the compile census stays
        flat."""
        rng = np.random.RandomState(7)
        # execution warmup (compiles already done by the fixture's warmup)
        engine.serve_tokens(prompts((4,)))
        before = engine.compiles
        for i in range(20):
            n = int(rng.randint(1, 17))
            res = engine.serve_tokens(
                [rng.randint(0, VOCAB, n).astype(np.int32)])
            assert res[0].tokens.shape == (4,)
        assert engine.compiles == before, "a request triggered a recompile"

    def test_packed_batch_equals_solo_serve(self, engine):
        """No cross-request leakage: a request served alone and served
        packed with unrelated company produces identical logits and
        tokens."""
        seqs = prompts((5, 8, 2), seed=11)
        solo = engine.serve_tokens([seqs[0]], return_prompt_logits=True)[0]
        packed = engine.serve_tokens(seqs, return_prompt_logits=True)[0]
        np.testing.assert_array_equal(solo.prompt_logits,
                                      packed.prompt_logits)
        np.testing.assert_array_equal(solo.tokens, packed.tokens)

    def test_greedy_tokens_consistent_with_logits(self, engine):
        res = engine.serve_tokens(prompts((6,)),
                                  return_prompt_logits=True)[0]
        assert res.tokens[0] == int(np.argmax(res.last_logits))

    def test_config_validation(self, mesh8, tiny):
        model, params = tiny
        with pytest.raises(ValueError, match="divide over the mesh"):
            InferenceEngine(model, mesh8,
                            ServeConfig(buckets=(8,), rows=3), params)
        with pytest.raises(ValueError, match="max_position"):
            InferenceEngine(
                model, mesh8,
                ServeConfig(buckets=(64,), rows=8, max_new_tokens=8),
                params)
        with pytest.raises(ValueError, match="serve_dtype"):
            ServeConfig(serve_dtype="fp16")
        with pytest.raises(ValueError, match="exceeds the largest bucket"):
            InferenceEngine(
                model, mesh8, ServeConfig(buckets=(8,), rows=8,
                                          max_new_tokens=4),
                params).serve_tokens(prompts((9,)))


class TestInt8Serving:
    def test_quantize_grid_matches_wire_codec(self, tiny):
        """The serve-side weight quantizer IS the wire codec's grid: same
        codes, same scales as grad_sync._quantize_int8_rows on the same
        rows."""
        from distributed_pytorch_training_tpu.parallel.grad_sync import (
            _quantize_int8_rows,
        )

        _model, params = tiny
        served = quantize_params(params, min_elements=64)
        leaves = {
            path: leaf for path, leaf in
            jax.tree_util.tree_leaves_with_path(
                served, is_leaf=lambda x: isinstance(x, QuantizedLeaf))}
        quantized = [(p, l) for p, l in leaves.items()
                     if isinstance(l, QuantizedLeaf)]
        assert quantized, "nothing got quantized"
        orig = dict(jax.tree_util.tree_leaves_with_path(params))
        for path, ql in quantized:
            rows = np.asarray(orig[path], np.float32).reshape(
                -1, orig[path].shape[-1])
            q_ref, s_ref = _quantize_int8_rows(jnp.asarray(rows),
                                               fused=False)
            np.testing.assert_array_equal(
                np.asarray(ql.q).reshape(q_ref.shape), np.asarray(q_ref))
            np.testing.assert_array_equal(
                np.asarray(ql.scale).ravel(), np.asarray(s_ref))

    def test_dequant_error_bound(self, tiny):
        """One-shot error <= scale/2 per element (the wire codec's bound,
        no error feedback — weights are static); un-quantized leaves pass
        through exact."""
        _model, params = tiny
        served = quantize_params(params, min_elements=64)
        deq = dequantize_params(served)
        flat_served = jax.tree_util.tree_leaves(
            served, is_leaf=lambda x: isinstance(x, QuantizedLeaf))
        flat_params = jax.tree_util.tree_leaves(params)
        flat_deq = jax.tree_util.tree_leaves(deq)
        checked = 0
        for sv, orig, back in zip(flat_served, flat_params, flat_deq):
            if not isinstance(sv, QuantizedLeaf):
                np.testing.assert_array_equal(np.asarray(orig),
                                              np.asarray(back))
                continue
            bound = np.asarray(sv.scale)[..., None] / 2 + 1e-12
            err = np.abs(np.asarray(orig, np.float32) - np.asarray(back))
            assert (err <= bound).all()
            checked += 1
        assert checked >= 2

    def test_grid_values_round_trip_exactly(self):
        """Integer-valued weights with the per-row absmax pinned to 127
        sit exactly on the codec grid (scale exactly 1.0) and round-trip
        bit-exactly — the wire codec's grid test, applied to weights."""
        rng = np.random.RandomState(0)
        w = rng.randint(-127, 128, (8, 256)).astype(np.float32)
        w[:, 0] = 127.0
        served = quantize_params(w, min_elements=1)
        assert isinstance(served, QuantizedLeaf)
        np.testing.assert_array_equal(np.asarray(served.scale), 1.0)
        np.testing.assert_array_equal(
            np.asarray(dequantize_params(served)), w)

    def test_int8_engine_serves_and_saves_bytes(self, mesh8, tiny):
        model, params = tiny
        eng = InferenceEngine(
            model, mesh8,
            ServeConfig(buckets=(8,), rows=8, max_new_tokens=2,
                        serve_dtype="int8", quantize_min_elements=64),
            params)
        res = eng.serve_tokens(prompts((5,)), return_prompt_logits=True)[0]
        assert res.prompt_logits.shape == (5, VOCAB)
        assert np.isfinite(res.prompt_logits).all()
        acct = int8_weight_bytes(eng._served)
        fp32_bytes = sum(4 * l.size
                         for l in jax.tree_util.tree_leaves(params))
        assert acct["quantized_bytes"] + acct["exact_bytes"] \
            < fp32_bytes / 2.5


# ---------------------------------------------------------------------------
# Checkpoint serving: restore_latest + provenance + torn-skip inheritance
# ---------------------------------------------------------------------------


class TestCheckpointServing:
    def _save_state(self, mesh8, model, tmp_path, labels=(1,), seed=0):
        from distributed_pytorch_training_tpu.training import (
            TrainConfig, Trainer,
        )
        from distributed_pytorch_training_tpu.training.checkpoint import (
            CheckpointManager,
        )
        from distributed_pytorch_training_tpu.training.optim import sgd
        from distributed_pytorch_training_tpu.training.tasks import (
            LanguageModelingTask,
        )

        trainer = Trainer(LanguageModelingTask(), mesh8,
                          TrainConfig(seed=0))
        state = trainer.init_state(model, np.zeros((1, 8), np.int32),
                                   sgd(0.1), jax.random.PRNGKey(seed))
        mgr = CheckpointManager(str(tmp_path), async_save=False)
        for label in labels:
            # distinct params per label so "which label restored" is
            # observable in the served logits
            state = state.replace(params=jax.tree_util.tree_map(
                lambda p: p + 0.01 * label, state.params))
            mgr.save(label, state, epoch=label)
        mgr.close()
        return state

    def test_from_checkpoint_serves_verified_weights(self, mesh8,
                                                     tmp_path):
        from distributed_pytorch_training_tpu.training.optim import sgd

        model = tiny_model()
        state = self._save_state(mesh8, model, tmp_path, labels=(1,))
        eng = InferenceEngine.from_checkpoint(
            str(tmp_path), model, mesh8,
            ServeConfig(buckets=(8,), rows=8, max_new_tokens=2),
            sgd(0.1), np.zeros((1, 8), np.int32))
        info = eng.checkpoint_info
        assert info["label"] == 1 and info["verified"]
        assert isinstance(info["tree_digest"], str) \
            and len(info["tree_digest"]) == 64
        # served logits come from the RESTORED params, bitwise
        seqs = prompts((6,))
        ids, _, _ = pack_token_rows(seqs, 8, 8)
        ev = jax.jit(lambda p, i: model.apply(
            {"params": p}, i, train=False))(
            state.params, shard_batch(ids, mesh8))
        res = eng.serve_tokens(seqs, return_prompt_logits=True)[0]
        np.testing.assert_array_equal(res.prompt_logits,
                                      np.asarray(ev)[0, :6])

    def test_torn_newest_falls_back_to_previous(self, mesh8, tmp_path):
        """Serving inherits the manifest-verified restore exactly: a torn
        newest checkpoint is skipped loudly and the previous valid one
        serves."""
        from distributed_pytorch_training_tpu.training.optim import sgd

        model = tiny_model()
        self._save_state(mesh8, model, tmp_path, labels=(1, 2))
        # tear label 2: truncate one of its array files
        victims = [p for p in (tmp_path / "2").rglob("*")
                   if p.is_file() and p.stat().st_size > 64]
        victims[0].write_bytes(b"torn")
        eng = InferenceEngine.from_checkpoint(
            str(tmp_path), model, mesh8,
            ServeConfig(buckets=(8,), rows=8, max_new_tokens=2),
            sgd(0.1), np.zeros((1, 8), np.int32))
        assert eng.checkpoint_info["label"] == 1

    def test_missing_checkpoint_is_loud(self, mesh8, tmp_path):
        from distributed_pytorch_training_tpu.training.optim import sgd

        with pytest.raises(FileNotFoundError, match="no restorable"):
            InferenceEngine.from_checkpoint(
                str(tmp_path / "empty"), tiny_model(), mesh8,
                ServeConfig(buckets=(8,), rows=8, max_new_tokens=2),
                sgd(0.1), np.zeros((1, 8), np.int32))


# ---------------------------------------------------------------------------
# Queue + continuous batching + drain
# ---------------------------------------------------------------------------


class TestBatching:
    def test_queue_groups_by_bucket_in_order(self):
        q = RequestQueue((8, 16))
        a = q.submit(np.ones(4, np.int32))
        b = q.submit(np.ones(12, np.int32))
        c = q.submit(np.ones(8, np.int32))
        group = q.next_batch(max_rows=8)
        # head (bucket 8) picks; c joins; b (bucket 16) stays queued
        assert [r.id for r in group] == [a.id, c.id]
        assert [r.id for r in q.next_batch(max_rows=8)] == [b.id]

    def test_submit_rejects_oversize_and_closed(self):
        q = RequestQueue((8,))
        with pytest.raises(ValueError, match="exceeds the largest bucket"):
            q.submit(np.ones(9, np.int32))
        q.close()
        with pytest.raises(RuntimeError, match="closed"):
            q.submit(np.ones(4, np.int32))

    def test_concurrent_submit_all_served(self, engine):
        q = RequestQueue(engine.config.buckets)
        stop = threading.Event()
        worker = threading.Thread(target=serve_forever,
                                  args=(engine, q, stop), daemon=True)
        worker.start()
        reqs = []
        lock = threading.Lock()

        def submitter(seed):
            for p in prompts((3, 9, 6), seed=seed):
                r = q.submit(p)
                with lock:
                    reqs.append(r)

        threads = [threading.Thread(target=submitter, args=(s,))
                   for s in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for r in reqs:
            res = r.result(timeout=120.0)
            assert res.tokens.shape == (engine.config.max_new_tokens,)
            assert r.t_done is not None
        stop.set()
        worker.join(timeout=30.0)
        assert not worker.is_alive()

    def test_drain_completes_pending_then_refuses(self, engine):
        q = RequestQueue(engine.config.buckets)
        pending = [q.submit(p) for p in prompts((4, 7), seed=5)]
        served = drain(engine, q)
        assert served == 2
        for r in pending:
            assert r.result(timeout=1.0).tokens.size
        with pytest.raises(RuntimeError, match="closed"):
            q.submit(np.ones(4, np.int32))

    def test_failed_batch_fails_requests_not_loop(self, engine,
                                                  monkeypatch):
        q = RequestQueue(engine.config.buckets)
        stop = threading.Event()
        real = engine.serve_tokens
        calls = {"n": 0}

        def flaky(seqs, **kw):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("injected")
            return real(seqs, **kw)

        monkeypatch.setattr(engine, "serve_tokens", flaky)
        worker = threading.Thread(target=serve_forever,
                                  args=(engine, q, stop), daemon=True)
        worker.start()
        bad = q.submit(np.ones(4, np.int32))
        with pytest.raises(RuntimeError, match="injected"):
            bad.result(timeout=60.0)
        good = q.submit(np.ones(4, np.int32))
        assert good.result(timeout=60.0).tokens.size
        stop.set()
        worker.join(timeout=30.0)


# ---------------------------------------------------------------------------
# The decode-step contract + the new analysis rules (mutation-tested)
# ---------------------------------------------------------------------------


class TestServingContract:
    def test_serving_decode_contract_passes_on_mesh(self, mesh8):
        from distributed_pytorch_training_tpu.analysis.hlo_rules import (
            check_artifacts, evaluate_contract,
        )
        from distributed_pytorch_training_tpu.analysis.contracts import (
            get_contract,
        )

        artifacts = evaluate_contract(get_contract("serving_decode"),
                                      mesh=mesh8)
        findings = check_artifacts(artifacts)
        assert findings == [], [str(f) for f in findings]

    def test_live_engine_artifacts_pass(self, engine):
        from distributed_pytorch_training_tpu.analysis.hlo_rules import (
            check_artifacts, serving_artifacts,
        )

        artifacts = serving_artifacts(engine, 16)
        assert check_artifacts(artifacts) == []
        assert artifacts.config["decode_cache_leaves"] == 4

    def test_mutation_missing_alias_entries_flag(self):
        from distributed_pytorch_training_tpu.analysis.hlo_rules import (
            StepArtifacts, check_artifacts,
        )

        partial = StepArtifacts(
            name="mut", optimized_text=(
                "HloModule decode, input_output_alias={ {0}: (28, {}, "
                "may-alias) }, entry_computation_layout={()}"),
            config={"serving_decode": True, "donate_state": True,
                    "decode_cache_leaves": 4})
        found = check_artifacts(partial, rules=["decode-cache-donated"])
        assert len(found) == 1 and "1 of the 4" in found[0].message
        absent = StepArtifacts(
            name="mut2", optimized_text="HloModule decode",
            config={"serving_decode": True, "donate_state": True,
                    "decode_cache_leaves": 4})
        assert check_artifacts(absent, rules=["decode-cache-donated"])
        # non-serving artifacts are out of scope
        train = StepArtifacts(name="t", optimized_text="HloModule x",
                              config={"donate_state": False})
        assert check_artifacts(train, rules=["decode-cache-donated"]) == []

    def test_mutation_host_transfer_in_decode_flags(self, engine):
        """The existing no-host-transfer rule binds on serving artifacts:
        a callback smuggled into the decode text is flagged with NO rule
        relaxation."""
        import dataclasses as dc

        from distributed_pytorch_training_tpu.analysis.hlo_rules import (
            check_artifacts, serving_artifacts,
        )

        artifacts = serving_artifacts(engine, 8)
        poisoned = dc.replace(
            artifacts, optimized_text=artifacts.optimized_text +
            '\n  custom-call(), custom_call_target="xla_python_cpu_callback"')
        found = check_artifacts(poisoned, rules=["no-host-transfer"])
        assert len(found) == 1

    def test_mutation_ast_host_sync_in_decode_flags(self, tmp_path):
        from distributed_pytorch_training_tpu.analysis.ast_rules import (
            run_ast_rules,
        )

        path = tmp_path / "serving" / "engine.py"
        path.parent.mkdir(parents=True)
        path.write_text(textwrap.dedent("""
            import jax

            def generate(self, cache, tok):
                for _ in range(4):
                    tok = jax.device_get(tok)
                return tok

            def serve_tokens(self, seqs):
                return jax.device_get(seqs)  # legal: after the loop
        """))
        found = run_ast_rules(files=[path],
                              rules=["no-host-sync-in-decode"])
        assert len(found) == 1 and "generate" in found[0].message

    def test_ast_rule_scopes_to_decode_loop_only(self, tmp_path):
        from distributed_pytorch_training_tpu.analysis.ast_rules import (
            run_ast_rules,
        )

        path = tmp_path / "serving" / "engine.py"
        path.parent.mkdir(parents=True)
        path.write_text(textwrap.dedent("""
            import jax

            def serve_tokens(self, seqs):
                return jax.device_get(seqs)
        """))
        assert run_ast_rules(files=[path],
                             rules=["no-host-sync-in-decode"]) == []
        # and the real engine passes its own rule
        assert run_ast_rules(rules=["no-host-sync-in-decode"]) == []


# ---------------------------------------------------------------------------
# Telemetry: serving phases in the per-phase split
# ---------------------------------------------------------------------------


class TestServingTelemetry:
    def test_summary_buckets_serving_phases(self):
        from distributed_pytorch_training_tpu.telemetry.__main__ import (
            summarize,
        )

        events = [{"kind": "meta", "name": "stream", "schema": 1,
                   "run_id": "r"}]
        for name, ms in (("queue_wait", 5.0), ("prefill", 20.0),
                         ("decode", 60.0), ("drain", 2.0)):
            events.append({"kind": "span", "name": name, "t0": 0.0,
                           "dur_ms": ms})
        s = summarize(events)
        assert set(s["step_split_pct"]) == {"queue_wait", "prefill",
                                            "decode", "drain"}
        assert abs(sum(s["step_split_pct"].values()) - 100.0) < 0.1

    def test_engine_emits_serving_spans(self, engine, tmp_path):
        from distributed_pytorch_training_tpu import telemetry
        from distributed_pytorch_training_tpu.telemetry.__main__ import (
            read_stream,
        )

        stream = tmp_path / "t.jsonl"
        telemetry.configure(str(stream))
        try:
            q = RequestQueue(engine.config.buckets)
            q.submit(np.ones(4, np.int32))
            drain(engine, q)
        finally:
            telemetry.reset()
        events, bad = read_stream(str(stream))
        assert bad == 0
        names = {e["name"] for e in events if e.get("kind") == "span"}
        assert {"queue_wait", "prefill", "decode", "drain"} <= names


# ---------------------------------------------------------------------------
# The bench row (fixed offered load) — the acceptance instrument
# ---------------------------------------------------------------------------


class TestMeasureServing:
    def test_bench_row_schema_and_zero_recompiles(self, mesh8, devices):
        from distributed_pytorch_training_tpu.experiments.harness import (
            measure_serving,
        )

        row = measure_serving(
            model_name="gpt2_124m", n_requests=20, offered_rps=200.0,
            buckets=(8, 16), rows=8, max_new_tokens=2,
            devices=devices,
            model_overrides=dict(hidden_dim=32, depth=2, num_heads=2,
                              vocab_size=VOCAB, max_position=32))
        assert row["mode"] == "serving"
        assert row["n_requests"] == 20
        assert row["recompiles_after_warmup"] == 0
        assert row["p50_ms"] > 0 and row["p99_ms"] >= row["p50_ms"]
        assert row["achieved_rps"] > 0 and row["tokens_per_sec"] > 0
        assert row["contracts"]["pass"] is True, row["contracts"]
        assert row["checkpoint"] is None  # random-init smoke, says so

    def test_bench_rejects_image_models_upfront(self, devices):
        from distributed_pytorch_training_tpu.experiments.harness import (
            measure_serving,
        )

        with pytest.raises(ValueError, match="serves images"):
            measure_serving(model_name="resnet18", n_requests=1,
                            devices=devices)

    def test_bert_bench_reports_no_phantom_tokens(self, mesh8, devices):
        """A bert (embedding) bench generates nothing: the row must not
        report a tokens_per_sec, and the decode contract reads as skipped
        rather than error."""
        from distributed_pytorch_training_tpu.experiments.harness import (
            measure_serving,
        )

        row = measure_serving(
            model_name="bert_base", n_requests=4, offered_rps=200.0,
            buckets=(8,), rows=8, max_new_tokens=2, devices=devices,
            model_overrides=dict(hidden_dim=32, depth=2, num_heads=2,
                              mlp_dim=64, vocab_size=97, max_position=64))
        assert "tokens_per_sec" not in row
        assert row["recompiles_after_warmup"] == 0
        assert row["contracts"]["pass"] is None
        assert "skipped" in row["contracts"]


class TestImageServing:
    def test_serve_images_and_normalization_cache_key(self, mesh8):
        """resnet classification serves through the engine, and the
        compiled-program cache keys on the normalization constants — a
        second call with different mean/std must NOT reuse the first
        call's baked-in values."""
        from distributed_pytorch_training_tpu.models import get_model

        model = get_model("resnet18", num_classes=4)
        variables = model.init(jax.random.PRNGKey(0),
                               np.zeros((1, 8, 8, 3), np.float32),
                               train=False)
        eng = InferenceEngine(
            model, mesh8, ServeConfig(buckets=(8,), rows=8),
            variables["params"], batch_stats=variables.get("batch_stats"))
        rng = np.random.RandomState(0)
        imgs = rng.randint(0, 256, (3, 8, 8, 3)).astype(np.uint8)
        mean, std = (0.5, 0.5, 0.5), (0.25, 0.25, 0.25)
        a = eng.serve_images(imgs, mean=mean, std=std)
        assert a.shape == (3, 4) and np.isfinite(a).all()
        compiles = eng.compiles
        # same stats: cached executable, no recompile
        np.testing.assert_array_equal(
            eng.serve_images(imgs, mean=mean, std=std), a)
        assert eng.compiles == compiles
        # different stats: MUST recompile and produce different logits
        b = eng.serve_images(imgs, mean=(0.1, 0.1, 0.1), std=(1.0, 1.0, 1.0))
        assert eng.compiles == compiles + 1
        assert not np.array_equal(a, b)


# ---------------------------------------------------------------------------
# CLI e2e (slow): checkpoint -> serving smoke subprocess
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestServingCLI:
    def test_smoke_serves_checkpoint_end_to_end(self, mesh8, tmp_path):
        from distributed_pytorch_training_tpu.training import (
            TrainConfig, Trainer,
        )
        from distributed_pytorch_training_tpu.training.checkpoint import (
            CheckpointManager,
        )
        from distributed_pytorch_training_tpu.training.optim import (
            make_optimizer, make_schedule,
        )
        from distributed_pytorch_training_tpu.training.tasks import (
            LanguageModelingTask,
        )

        model = tiny_model(vocab_size=50257, max_position=64)
        trainer = Trainer(LanguageModelingTask(), mesh8,
                          TrainConfig(seed=0))
        # the chain train.py builds (make_optimizer + callable schedule,
        # no clip) — the serving CLI's auto template must match it
        tx = make_optimizer("adamw", make_schedule("constant", 1e-4))
        state = trainer.init_state(model, np.zeros((1, 8), np.int32),
                                   tx, jax.random.PRNGKey(0))
        mgr = CheckpointManager(str(tmp_path / "ckpt"), async_save=False)
        mgr.save(1, state, epoch=1)
        mgr.close()

        import os

        env = dict(os.environ,
                   JAX_PLATFORMS="cpu",
                   XLA_FLAGS="--xla_force_host_platform_device_count=8")
        out = subprocess.run(
            [sys.executable, "-m",
             "distributed_pytorch_training_tpu.serving", "smoke",
             "--model", "gpt2_124m",
             "--model-overrides",
             "hidden_dim=32,depth=2,num_heads=2",
             "--ckpt-dir", str(tmp_path / "ckpt"),
             "--buckets", "8,16", "--rows", "8", "--max-new-tokens", "2",
             "--output-dir", str(tmp_path / "out")],
            env=env, cwd=str(Path(__file__).resolve().parent.parent),
            capture_output=True, text=True, timeout=600)
        assert out.returncode == 0, out.stdout + out.stderr
        text = out.stdout + out.stderr
        assert "tree_digest" in text and "serving smoke: ok" in text
        # the telemetry stream landed with serving spans
        stream = tmp_path / "out" / "telemetry_rank0.jsonl"
        assert stream.exists()
        names = {json.loads(l).get("name")
                 for l in stream.read_text().splitlines() if l.strip()}
        assert {"queue_wait", "prefill", "decode"} <= names
