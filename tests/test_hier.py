"""Two-tier topology-aware gradient sync (ISSUE 16: wire_dtype='int8_hier'
on a sliced mesh — exact fp32 reduce-scatter/all-gather INSIDE a slice,
compressed s8 + error-feedback multihop exchange ACROSS slices).

The contracts pinned here:

(a) **Parity.** The hierarchical wire is a perturbation of the slow tier
    only: 20-step loss trajectories track flat fp32 at the compressed
    tolerance (grad-accum off AND on), and the slow-tier EF residual rows
    (the 1/n_inner partial layout) are alive after a step.

(b) **slices=1 passthrough.** int8_hier on a mesh without a real slice
    axis resolves to the flat fp32 path BEFORE tracing — trajectories and
    params are BIT-identical to wire_dtype='fp32' (loop.py documents this
    file as the pin).

(c) **Codec math.** `_int8_hier_sum` via `reduce_flat` on the real
    (slice=2, data=4) CPU mesh: grid values round-trip bit-exactly, the
    one-shot error obeys the two-quantization bound on the SLOW tier only
    (the fast tier is exact by construction), and the slow-tier EF
    telescopes.

(d) **Wire accounting.** `hier_wire_bytes`: per-slice slow-tier bytes are
    INDEPENDENT of the slice count (the point of the hierarchy), the fast
    tier prices as flat fp32 at the per-slice degree, infeasible
    factorizations raise.

(e) **The tier census.** The gsync_int8_hier contract lowers clean under
    the full rule suite with exactly n_buckets collectives per hop per
    tier, and `hier-tier-signature` / `no-fp32-wire` flag each synthetic
    mutation (flat traffic wearing the two-tier flag, a missing hop, f32
    crossing slices) while abstaining on the slices=1 passthrough.
"""

import jax
import numpy as np
import pytest

from jax.sharding import PartitionSpec as P

from distributed_pytorch_training_tpu.models.gpt2 import GPT2LMHead
from distributed_pytorch_training_tpu.parallel import (
    MeshSpec, build_mesh, shard_batch,
)
from distributed_pytorch_training_tpu.parallel.collectives import shard_map
from distributed_pytorch_training_tpu.parallel.grad_sync import (
    HierSpec, build_bucket_plan, hier_wire_bytes, padded_total_size,
    reduce_flat, wire_bytes_per_replica,
)
from distributed_pytorch_training_tpu.training import TrainConfig, Trainer
from distributed_pytorch_training_tpu.training.optim import sgd
from distributed_pytorch_training_tpu.training.tasks import LanguageModelingTask

SEQ = 16
VOCAB = 64

# The test topology: 2 slices x 4 intra-slice shards on the 8 virtual CPU
# devices — the same factorization the hier contracts lower on.
N_SLICES = 2
N_INNER = 4
HSPEC = HierSpec(slice_axis="slice", fast_axes=("data",),
                 n_slices=N_SLICES, n_inner=N_INNER)


@pytest.fixture(scope="module")
def hier_mesh(devices):
    return build_mesh(MeshSpec.parse("slice=2,data=4"), devices=devices)


def _tiny_gpt2():
    return GPT2LMHead(vocab_size=VOCAB, hidden_dim=32, depth=2, num_heads=2,
                      max_position=SEQ)


def _trainer(mesh, **cfg):
    t = Trainer(LanguageModelingTask(), mesh, TrainConfig(seed=0, **cfg))
    state = t.init_state(_tiny_gpt2(), np.zeros((1, SEQ), np.int32),
                         sgd(0.1, momentum=0.9, weight_decay=5e-4),
                         jax.random.PRNGKey(0))
    return t, state


def _batch(mesh, n=16):
    rng = np.random.RandomState(0)
    return shard_batch({
        "input_ids": rng.randint(0, VOCAB, (n, SEQ)).astype(np.int32),
        "weight": np.ones(n, np.float32),
    }, mesh)


def _run(mesh, steps=4, **cfg):
    t, s = _trainer(mesh, **cfg)
    batch = _batch(mesh)
    key = jax.random.PRNGKey(1)
    losses = []
    for _ in range(steps):
        s, m = t._train_step(s, batch, key)
        losses.append(float(m["loss_sum"]) / max(float(m["weight"]), 1.0))
    return losses, s


# ---------------------------------------------------------------------------
# (a) Parity on the sliced mesh
# ---------------------------------------------------------------------------


def test_hier_parity_20_steps(hier_mesh):
    """ISSUE-16 acceptance: fp32-vs-int8_hier loss trajectories agree
    within tolerance over 20 steps on the (slice=2, data=4) mesh. The fast
    tier is exact, so all perturbation comes from the slow-tier multihop
    on the 1/n_inner partial — same error model as int8_multihop, smaller
    payload."""
    l_fp, _ = _run(hier_mesh, steps=20)
    l_h, s_h = _run(hier_mesh, steps=20, bucket_cap_mb=0.05,
                    wire_dtype="int8_hier")
    assert l_h[-1] < l_h[0]
    np.testing.assert_allclose(l_fp, l_h, rtol=3e-2)
    # slow-tier EF residuals: per-replica rows over the 1/n_inner view of
    # the padded layout (ONE feedback site, on the slow tier)
    plan = build_bucket_plan(s_h.params, 0.05)
    ef = np.asarray(jax.device_get(s_h.grad_sync["ef"]))
    assert ef.shape == (8, padded_total_size(plan, 8) // N_INNER)
    assert np.abs(ef).max() > 0.0


@pytest.mark.slow  # ~9 s; the non-accum hier parity stays fast and the accum interaction is gated by the gsync_int8_hier_accum matrix contract
def test_hier_parity_20_steps_grad_accum(hier_mesh):
    """Grad-accum ON: the slow-tier residual is carried through the
    microbatch scan. Per-step bound coarse, time-averaged tail tight —
    the multihop grad-accum test documents why (this tiny high-LR task is
    chaotic by step ~18)."""
    l_fp, _ = _run(hier_mesh, steps=20, grad_accum=2)
    l_h, _ = _run(hier_mesh, steps=20, grad_accum=2, bucket_cap_mb=0.05,
                  wire_dtype="int8_hier")
    assert l_h[-1] < l_h[0]
    np.testing.assert_allclose(l_fp, l_h, rtol=1.5e-1)
    np.testing.assert_allclose(np.mean(l_fp[-5:]), np.mean(l_h[-5:]),
                               rtol=2e-2)


@pytest.mark.slow
def test_zero1_hier_parity_20_steps(hier_mesh):
    """zero1 x int8_hier (the zero1_int8_hier contract's training-side
    twin): sharded optimizer state with the tiered wire still tracks fp32
    at lr=0.05 (the zero1 multihop test documents the saner-LR choice).

    Slow tier: the fast gate already lowers and tier-checks this exact
    composition via the zero1_int8_hier contract in the analysis matrix."""
    def run(wire):
        t = Trainer(LanguageModelingTask(), hier_mesh,
                    TrainConfig(seed=0, zero1=True, wire_dtype=wire))
        s = t.init_state(_tiny_gpt2(), np.zeros((1, SEQ), np.int32),
                         sgd(0.05, momentum=0.9, weight_decay=5e-4),
                         jax.random.PRNGKey(0))
        batch = _batch(hier_mesh)
        key = jax.random.PRNGKey(1)
        losses = []
        for _ in range(20):
            s, m = t._train_step(s, batch, key)
            losses.append(float(m["loss_sum"])
                          / max(float(m["weight"]), 1.0))
        return losses

    l_fp = run("fp32")
    l_h = run("int8_hier")
    assert l_h[-1] < l_h[0]
    np.testing.assert_allclose(l_fp, l_h, rtol=3e-2)


# ---------------------------------------------------------------------------
# (b) slices=1 passthrough: bit-identical to the flat fp32 wire
# ---------------------------------------------------------------------------


def test_slices1_passthrough_is_bitwise_fp32(mesh8):
    """On a mesh without a real slice axis the trainer resolves int8_hier
    to the flat fp32 path BEFORE tracing (loop.py pins this file): same
    compiled program, bit-identical trajectory and params."""
    t_h, s_h = _trainer(mesh8, bucket_cap_mb=0.05, wire_dtype="int8_hier")
    assert t_h._hier is None and t_h._wire == "fp32"
    l_h, s_h = _run(mesh8, steps=3, bucket_cap_mb=0.05,
                    wire_dtype="int8_hier")
    l_fp, s_fp = _run(mesh8, steps=3, bucket_cap_mb=0.05)
    assert l_h == l_fp  # exact equality, not allclose
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b))),
        s_h.params, s_fp.params)


def test_wire_accounting_inputs_record_resolved_topology(hier_mesh, mesh8):
    """The accounting assembly both train.py and bench use: on a sliced
    mesh the resolved slice count is injected (the factorization lives in
    the MESH, not the caller's config dict); on a slice-free mesh the
    passthrough records the flat fp32 wire it actually runs."""
    cfg_in = {"wire_dtype": "int8_hier", "bucket_cap_mb": 0.05}
    t, s = _trainer(hier_mesh, **cfg_in)
    _, cfg = t.wire_accounting_inputs(s, cfg_in, 16, SEQ)
    assert cfg["slices"] == N_SLICES
    assert cfg["wire_dtype"] == "int8_hier"
    t1, s1 = _trainer(mesh8, **cfg_in)
    _, cfg1 = t1.wire_accounting_inputs(s1, cfg_in, 16, SEQ)
    assert cfg1["wire_dtype"] == "fp32"
    assert "slices" not in cfg1


# ---------------------------------------------------------------------------
# (c) Codec math on the real (slice=2, data=4) mesh
# ---------------------------------------------------------------------------


def _hier_reduce_fn(mesh, plan):
    """jitted (contribs (8, S), ef (8, R)) -> (sums (8, S), new ef): the
    hier codec run inside a shard_map over the sliced mesh, one
    contribution row per replica (row r = slice r//4, fast rank r%4 —
    slice-major device ids, mesh.AXIS_ORDER)."""
    def body(x, ef):
        out, new_ef = reduce_flat(x.reshape(-1), plan, ("slice", "data"), 8,
                                  "int8_hier", ef.reshape(-1), hier=HSPEC)
        return out[None], new_ef[None]

    spec = P(("slice", "data"))
    return jax.jit(shard_map(body, mesh, in_specs=(spec, spec),
                             out_specs=(spec, spec)))


class TestHierCodec:
    """Unit contracts of `_int8_hier_sum` via `reduce_flat` (real
    collectives on the sliced CPU mesh, no cluster)."""

    S = 1000  # not divisible by 8 — exercises the padded layout
    CAP = 400 * 4 / (1024 ** 2)  # 400-element buckets: sizes 400/400/200

    def _plan(self):
        return build_bucket_plan({"a": np.zeros(self.S)}, self.CAP)

    def _ef0(self, plan):
        # slow-tier residual: the 1/n_inner view of the padded layout
        return np.zeros((8, padded_total_size(plan, 8) // N_INNER),
                        np.float32)

    def test_exact_on_grid_values(self, hier_mesh):
        """Integer contributions with every chunk's max-abs pinned to 127:
        the intra-slice partial is 4x an integer row (max-abs 508 -> the
        slow-tier hop-1 scale is EXACTLY 4.0 in fp32, hop-2's exactly 8.0
        — power-of-two multiples of the 127 grid), so the full two-tier
        round trip is bit-exact with an all-zero residual. Any deviation
        is codec math, not quantization."""
        plan = self._plan()
        rng = np.random.RandomState(0)
        row = rng.randint(-127, 128, self.S).astype(np.float32)
        row[::10] = 127.0
        contribs = np.tile(row, (8, 1))
        out, ef = _hier_reduce_fn(hier_mesh, plan)(contribs, self._ef0(plan))
        np.testing.assert_array_equal(np.asarray(out)[0], 8.0 * row)
        np.testing.assert_array_equal(np.asarray(ef), 0.0)

    def test_one_shot_error_bounded_by_slow_tier_quanta(self, hier_mesh):
        """|hier - exact| obeys the multihop two-quantization bound
        computed on the INTRA-SLICE PARTIAL SUMS (the only values that
        ever meet a quantizer — the fast tier is exact): hop-1 half-quanta
        of the n_slices senders plus the hop-2 half-quantum."""
        plan = self._plan()
        rng = np.random.RandomState(1)
        contribs = rng.randn(8, self.S).astype(np.float32)
        exact = contribs.sum(0)
        # the slow tier quantizes the per-slice partials, not raw rows
        inner = contribs.reshape(N_SLICES, N_INNER, self.S).sum(1)
        out, ef = _hier_reduce_fn(hier_mesh, plan)(contribs, self._ef0(plan))
        out = np.asarray(out)[0]
        for a, b in zip(plan.bounds, plan.bounds[1:]):
            seg = slice(a, b)
            hop1 = N_SLICES * (np.abs(inner[:, seg]).max() / 127.0) / 2
            hop2 = (np.abs(exact[seg]).max() + hop1) / 127.0 / 2
            err = np.abs(out[seg] - exact[seg]).max()
            assert err <= hop1 + hop2 + 1e-5, (a, b, err, hop1, hop2)
        # the slow-tier residual is alive (error feedback engaged)
        assert np.abs(np.asarray(ef)).max() > 0.0

    def test_slow_tier_error_feedback_telescopes(self, hier_mesh):
        """Repeated reduction of the SAME contributions: the hop-1 bias
        telescopes through the single slow-tier EF site, so the cumulative
        MEAN improves on the one-shot error and settles at the un-fed-back
        hop-2 noise — bounded per bucket by the hop-2 HALF-quantum (the
        multihop precedent asserts one_shot/2 instead, but with only
        n_slices=2 slow-tier senders hop-1's share of the one-shot error
        is small; the half-quantum bound is the tier-correct claim). A
        codec that drops its residual keeps the full one-shot bias
        (~2x the half-quantum here) at every horizon and fails both
        assertions."""
        plan = self._plan()
        rng = np.random.RandomState(2)
        contribs = rng.randn(8, self.S).astype(np.float32)
        exact = contribs.sum(0)
        inner = contribs.reshape(N_SLICES, N_INNER, self.S).sum(1)
        f = _hier_reduce_fn(hier_mesh, plan)
        ef = self._ef0(plan)
        out1, _ = f(contribs, np.zeros_like(ef))
        one_shot = np.abs(np.asarray(out1)[0] - exact).max()
        cum = np.zeros(self.S)
        steps = 12
        for _ in range(steps):
            out, ef = f(contribs, ef)
            cum += np.asarray(out)[0]
        mean = cum / steps
        assert np.abs(mean - exact).max() < one_shot
        for a, b in zip(plan.bounds, plan.bounds[1:]):
            seg = slice(a, b)
            hop1 = N_SLICES * (np.abs(inner[:, seg]).max() / 127.0) / 2
            halfq2 = (np.abs(exact[seg]).max() + hop1) / 127.0 / 2
            mean_err = np.abs(mean[seg] - exact[seg]).max()
            assert mean_err <= halfq2 + 1e-5, (a, b, mean_err, halfq2)


# ---------------------------------------------------------------------------
# (d) Wire-byte accounting: the hierarchy's scaling property
# ---------------------------------------------------------------------------


class TestHierWireBytes:
    """`hier_wire_bytes`: the two-tier byte formulas as code, across
    (slices, per_slice) factorizations."""

    def _plan(self, total=4096, bucket=1024):
        # bucket sizes divisible by 16 -> zero padding at every world
        # size used here, so the formulas are exact, not bounds
        return build_bucket_plan({"a": np.zeros(total)},
                                 bucket * 4 / (1024 ** 2))

    def test_slow_tier_bytes_per_slice_independent_of_slice_count(self):
        """THE property the hierarchy exists for: summed over a slice's
        n_inner replicas, the DCN bytes are 2*S_padded per slice no matter
        how many slices the fleet has — scaling out adds slices, not
        per-slice slow-tier traffic. (Flat multihop's 2*S_padded rides
        links that are ALL slow once the mesh spans pods.)"""
        plan = self._plan()
        s_padded = padded_total_size(plan, 8)
        for n_shards, n_slices in ((4, 2), (8, 2), (8, 4)):
            n_inner = n_shards // n_slices
            split = hier_wire_bytes(plan, n_shards, n_slices)
            assert split["dcn"] * n_inner == 2 * s_padded, \
                (n_shards, n_slices)
        # same n_inner, different slice count: identical per-replica split
        assert hier_wire_bytes(plan, 4, 2) == hier_wire_bytes(plan, 8, 4)

    def test_fast_tier_prices_as_flat_fp32_at_per_slice_degree(self):
        plan = self._plan()
        for n_shards, n_slices in ((4, 2), (8, 2), (8, 4)):
            n_inner = n_shards // n_slices
            split = hier_wire_bytes(plan, n_shards, n_slices)
            if n_inner > 1:
                assert split["ici"] == wire_bytes_per_replica(
                    plan, "fp32", n_inner)
            # the mode-table total is the tier sum
            assert wire_bytes_per_replica(
                plan, "int8_hier", n_shards, n_slices) == \
                split["ici"] + split["dcn"]

    def test_no_fast_tier_when_every_shard_is_its_own_slice(self):
        plan = self._plan()
        split = hier_wire_bytes(plan, 4, 4)  # n_inner == 1
        assert split["ici"] == 0
        assert split["dcn"] == 2 * padded_total_size(plan, 4)

    def test_slices1_prices_as_flat_fp32(self):
        plan = self._plan()
        assert hier_wire_bytes(plan, 8, 1) == \
            {"ici": wire_bytes_per_replica(plan, "fp32", 8), "dcn": 0}

    def test_infeasible_factorizations_raise(self):
        plan = self._plan()
        with pytest.raises(ValueError, match="do not factor into"):
            hier_wire_bytes(plan, 8, 3)
        with pytest.raises(ValueError, match="n_slices must be >= 1"):
            hier_wire_bytes(plan, 8, 0)


# ---------------------------------------------------------------------------
# Guards: the seams where a bad topology must fail loudly
# ---------------------------------------------------------------------------


class TestHierGuards:
    def test_rejects_non_batch_slice_axis(self, mesh8):
        with pytest.raises(ValueError, match="is not one of them"):
            Trainer(LanguageModelingTask(), mesh8,
                    TrainConfig(wire_dtype="int8_hier", slice_axis="model"))

    def test_rejects_explicit_tp_composition(self, devices):
        mesh2d = build_mesh(MeshSpec.parse("data=4,model=2"),
                            devices=devices)
        with pytest.raises(ValueError,
                           match="does not compose with explicit TP"):
            Trainer(LanguageModelingTask(), mesh2d,
                    TrainConfig(wire_dtype="int8_hier", fsdp_explicit=True))

    def test_hierspec_rejects_degenerate_topologies(self):
        with pytest.raises(ValueError, match=">= 2 slices"):
            HierSpec(slice_axis="slice", fast_axes=("data",),
                     n_slices=1, n_inner=4)

    def test_reduce_flat_requires_spec_and_residual(self):
        plan = build_bucket_plan({"a": np.zeros(64)}, 0.0)
        flat = np.zeros(64, np.float32)
        with pytest.raises(ValueError, match="needs a HierSpec"):
            reduce_flat(flat, plan, ("slice", "data"), 8, "int8_hier",
                        residual=np.zeros(16, np.float32))
        with pytest.raises(ValueError, match="error-feedback"):
            reduce_flat(flat, plan, ("slice", "data"), 8, "int8_hier",
                        hier=HSPEC)


# ---------------------------------------------------------------------------
# (e) The tier census: contract + rule mutations
# ---------------------------------------------------------------------------


@pytest.mark.slow  # ~5 s; strictly redundant with the gsync_int8_hier contract in the matrix gate
def test_gsync_hier_contract_clean_and_tier_pure(devices):
    """The ISSUE-16 acceptance contract, evaluated directly: the lowered
    step is clean under the FULL rule suite and its census is tier-pure —
    exactly n_buckets collectives per hop per tier, s8 (never f32) on
    every cross-slice row."""
    from distributed_pytorch_training_tpu.analysis.contracts import (
        CONTRACT_MATRIX,
    )
    from distributed_pytorch_training_tpu.analysis.hlo_rules import (
        check_artifacts, evaluate_contract, expected_buckets,
        grad_sync_census,
    )

    c = next(x for x in CONTRACT_MATRIX if x.name == "gsync_int8_hier")
    a = evaluate_contract(c)
    assert a.slice_shards == N_SLICES and a.hier_engaged
    assert check_artifacts(a) == []
    n_buckets = expected_buckets(a.total_grad_bytes,
                                 float(c.config["bucket_cap_mb"]))
    assert n_buckets > 1  # the cap really cuts — per-bucket counts bind
    census = grad_sync_census(a.optimized_text, a.min_elements)
    by = {}
    for r in census["rows"]:
        key = (a.collective_tier(r), r["op"])
        by[key] = by.get(key, 0) + r["count"]
    assert by == {("ici", "reduce-scatter"): n_buckets,
                  ("ici", "all-gather"): n_buckets,
                  ("dcn", "all-to-all"): n_buckets,
                  ("dcn", "all-gather"): n_buckets}, by
    wrows = grad_sync_census(a.wire_text, a.min_elements)["rows"]
    dcn_rows = [r for r in wrows if a.collective_tier(r) == "dcn"]
    assert dcn_rows and all("f32" not in r["dtypes"] for r in dcn_rows)
    assert any("s8" in r["dtypes"] for r in dcn_rows)


# --- synthetic-HLO mutation tests ------------------------------------------

ICI_G = "{{0,1,2,3},{4,5,6,7}}"      # consecutive runs of n_inner
DCN_G = "{{0,4},{1,5},{2,6},{3,7}}"  # stride-n_inner combs
ALL_G = "{{0,1,2,3,4,5,6,7}}"        # spanning — flat traffic

HEADER = ("HloModule jit_step, is_scheduled=true, "
          "input_output_alias={ {0}: (0, {}, may-alias) }, "
          "entry_computation_layout={(f32[64]{0})->f32[64]{0}}")


def _coll(name, op, dt, n, groups, operand_n=None):
    shp = dt + "[" + str(n) + "]{0}"
    oshp = dt + "[" + str(operand_n if operand_n else n) + "]{0}"
    return ("  %" + name + " = " + shp + " " + op + "(" + oshp +
            " %p), dimensions={0}, replica_groups=" + groups)


def _hier_lines():
    """One bucket's full two-tier signature (16384-element slow part —
    above the 8192 census floor)."""
    return [
        _coll("rs", "reduce-scatter", "f32", 16384, ICI_G, 65536),
        _coll("a2a", "all-to-all", "s8", 16384, DCN_G),
        _coll("agd", "all-gather", "s8", 16384, DCN_G, 8192),
        _coll("agi", "all-gather", "f32", 65536, ICI_G, 16384),
    ]


def _hier_artifacts(body_lines, preopt_lines=None, **kw):
    from distributed_pytorch_training_tpu.analysis.hlo_rules import (
        StepArtifacts,
    )

    def module(lines):
        return HEADER + "\n\nENTRY %main {\n" + "\n".join(lines) + "\n}\n"

    kw.setdefault("n_shards", 8)
    kw.setdefault("slice_shards", 2)
    kw.setdefault("min_elements", 8192)
    kw.setdefault("config", dict(wire_dtype="int8_hier"))
    # one huge bucket (no cap): part = 65536/1/4 = 16384 >= the floor, so
    # the exact per-bucket count arm binds at n_buckets=1
    kw.setdefault("total_grad_bytes", 65536 * 4)
    return StepArtifacts(
        name="synthetic", optimized_text=module(body_lines),
        preopt_text=module(preopt_lines) if preopt_lines else None, **kw)


def _run_rule(a, rule):
    from distributed_pytorch_training_tpu.analysis.hlo_rules import (
        check_artifacts,
    )

    return check_artifacts(a, rules=[rule])


class TestHierTierSignatureRule:
    def test_full_signature_is_clean(self):
        a = _hier_artifacts(_hier_lines(), preopt_lines=_hier_lines())
        assert _run_rule(a, "hier-tier-signature") == []

    def test_mutation_missing_slow_scatter_flags(self):
        lines = [ln for ln in _hier_lines() if "%a2a" not in ln]
        fs = _run_rule(_hier_artifacts(lines), "hier-tier-signature")
        assert any("hop 1" in f.message for f in fs), fs

    def test_mutation_missing_slow_gather_flags(self):
        lines = [ln for ln in _hier_lines() if "%agd" not in ln]
        fs = _run_rule(_hier_artifacts(lines), "hier-tier-signature")
        assert any("hop 2" in f.message for f in fs), fs

    def test_mutation_missing_fast_reduce_flags(self):
        lines = [ln for ln in _hier_lines() if "%rs " not in ln]
        fs = _run_rule(_hier_artifacts(lines), "hier-tier-signature")
        assert any("fast-tier reduce is missing" in f.message
                   for f in fs), fs

    def test_mutation_missing_fast_gather_flags(self):
        lines = [ln for ln in _hier_lines() if "%agi" not in ln]
        fs = _run_rule(_hier_artifacts(lines), "hier-tier-signature")
        assert any("never rebuilt" in f.message for f in fs), fs

    def test_mutation_spanning_groups_flag_flat_traffic(self):
        """A flat multihop mislabeled int8_hier: its groups span the whole
        mesh — neither tier claims them."""
        lines = _hier_lines() + [
            _coll("flat", "all-to-all", "s8", 16384, ALL_G)]
        fs = _run_rule(_hier_artifacts(lines), "hier-tier-signature")
        assert any("neither intra-slice nor cross-slice" in f.message
                   for f in fs), fs

    def test_mutation_extra_hop_breaks_per_bucket_count(self):
        lines = _hier_lines() + [
            _coll("a2a2", "all-to-all", "s8", 16384, DCN_G)]
        fs = _run_rule(_hier_artifacts(lines), "hier-tier-signature")
        assert any("expected exactly 1" in f.message for f in fs), fs

    def test_mutation_f32_crossing_slices_flags(self):
        """A decompressed hop-2 paying 4x on the slow links — the dtype
        arm reads the pre-opt text like every wire rule."""
        preopt = _hier_lines() + [
            _coll("agf", "all-gather", "f32", 16384, DCN_G, 8192)]
        fs = _run_rule(_hier_artifacts(_hier_lines(), preopt_lines=preopt),
                       "hier-tier-signature")
        assert any("CROSS-SLICE collective(s) carry f32" in f.message
                   for f in fs), fs

    def test_abstains_on_slices1_passthrough(self):
        """slice_shards=1: the trainer resolved to the flat fp32 path —
        no hier collective exists; every wire rule must abstain even on
        text that would otherwise scream."""
        garbage = [_coll("ar", "all-reduce", "f32", 16384, ALL_G)]
        a = _hier_artifacts(garbage, preopt_lines=garbage, slice_shards=1)
        assert not a.hier_engaged
        for rule in ("hier-tier-signature", "no-fp32-wire",
                     "compressed-wire"):
            assert _run_rule(a, rule) == [], rule


class TestNoFp32WireHierExemption:
    def test_fast_tier_f32_is_exempt_when_hier_engaged(self):
        """The intra-slice stage reduces in exact fp32 BY DESIGN — only
        the ici tier is exempt from the no-fp32 promise."""
        a = _hier_artifacts(_hier_lines(), preopt_lines=_hier_lines())
        assert _run_rule(a, "no-fp32-wire") == []

    def test_spanning_f32_reduction_still_flags(self):
        preopt = _hier_lines() + [
            _coll("ar", "all-reduce", "f32", 16384, ALL_G)]
        fs = _run_rule(_hier_artifacts(_hier_lines(), preopt_lines=preopt),
                       "no-fp32-wire")
        assert fs and "f32" in fs[0].message
