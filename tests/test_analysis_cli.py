"""`analysis check` CLI (analysis/__main__.py): the tier-1 gate — the full
rule suite over the repo source AND the canonical config matrix lowered on
the CPU test mesh must exit 0 (ISSUE 3 acceptance).
"""

import json

from distributed_pytorch_training_tpu.analysis.__main__ import main


def test_analysis_check_json_exits_0_on_repo(capsys, devices):
    """THE acceptance test: every AST rule over the repo plus every HLO
    contract in the matrix (dp / zero1 / grad_sync x wires / accum /
    explicit FSDP / the serving decode step), lowered on the 8-device CPU
    mesh — clean, and every contract really evaluated (a matrix of skips
    would be vacuously green)."""
    assert main(["check", "--json"]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["schema_version"] == 2
    assert report["ok"] is True and report["findings"] == []
    statuses = report["contracts"]
    assert set(statuses) == {"dp", "dp_accum", "zero1", "zero1_bf16",
                             "zero1_int8_mh",
                             "gsync_fp32", "gsync_bf16", "gsync_int8",
                             "gsync_bf16_accum", "gsync_int8_mh",
                             "gsync_int8_mh_accum", "gsync_int8_mh_fused",
                             "gsync_int8_hier", "gsync_int8_hier_accum",
                             "zero1_int8_hier",
                             "fsdp", "fsdp_accum", "fsdp_int8_mh",
                             "fsdp_tp", "fsdp_tp_int8_mh",
                             "serving_decode", "serving_paged",
                             "serving_spec",
                             "control_replan",
                             "elastic_reshard",
                             "elastic_grow"}
    assert all(s == "pass" for s in statuses.values()), statuses
    # both engines actually ran, incl. the fsdp rules (ISSUE 7), the
    # serving decode-step rules (ISSUE 10), the elastic census pins in
    # BOTH directions (ISSUEs 11 + 12), the 2-D TP x FSDP rules
    # (ISSUE 13), the two-tier hier wire rules (ISSUE 16), and the paged
    # serving pool donation rule (ISSUE 17)
    kinds = {r for r in report["rules_run"]}
    assert "shard-map-shim-only" in kinds and "zero1-collectives" in kinds
    assert "fsdp-layer-gather-bound" in kinds
    assert "decode-cache-donated" in kinds
    assert "no-host-sync-in-decode" in kinds
    assert "elastic-reshard-census" in kinds
    assert "elastic-grow-census" in kinds
    assert "tp-psum-signature" in kinds
    assert "hier-tier-signature" in kinds
    assert "paged-pool-donated" in kinds
    assert "fsdp-gather-rides-data-only" in kinds
    assert "span-names-registered" in kinds
    assert "profiler-session-via-stepprofiler-only" in kinds
    # the speculative verify-path donation rule (ISSUE 19)
    assert "spec-verify-donated" in kinds
    # the concurrency discipline pass (ISSUE 18)
    assert "guarded-by" in kinds
    assert "lock-order-acyclic" in kinds
    assert "no-blocking-under-lock" in kinds
    assert "thread-lifecycle" in kinds
    # the control-plane gate (ISSUE 20)
    assert "control-decisions-gated" in kinds


def test_ast_only_is_fast_and_clean(capsys):
    assert main(["check", "--ast-only"]) == 0
    out = capsys.readouterr().out
    assert "0 finding(s)" in out
    assert "contract" not in out  # no HLO matrix ran


def test_rules_selection_and_unknown_rule(capsys):
    assert main(["check", "--ast-only", "--rules",
                 "shard-map-shim-only,axis-name-registry"]) == 0
    assert main(["check", "--rules", "no-such-rule"]) == 2
    assert "no-such-rule" in capsys.readouterr().err


def test_unknown_contract_is_a_usage_error(capsys):
    assert main(["check", "--contracts", "warp-drive"]) == 2
    assert "warp-drive" in capsys.readouterr().err


def test_list_prints_catalog_with_rationales(capsys):
    assert main(["check", "--list"]) == 0
    out = capsys.readouterr().out
    for name in ("shard-map-shim-only", "no-impure-calls-in-traced",
                 "no-host-sync-in-step", "axis-name-registry",
                 "grad-sync-bucket-bound", "compressed-wire",
                 "no-fp32-wire", "zero1-collectives", "zero1-sharded-state",
                 "donated-buffers-elided", "no-host-transfer",
                 "dp-sync-present"):
        assert name in out, name
    assert "why:" in out


def test_console_script_is_declared():
    """The pyproject entry point must keep pointing at main (ISSUE 3
    satellite: `analysis` console script)."""
    from pathlib import Path

    pyproject = (Path(__file__).resolve().parent.parent
                 / "pyproject.toml").read_text()
    assert ('analysis = "distributed_pytorch_training_tpu.analysis.'
            '__main__:main"') in pyproject


def test_findings_drive_nonzero_exit(tmp_path, capsys, monkeypatch):
    """A violation anywhere in the linted set must flip the exit code —
    the CLI's one job."""
    from distributed_pytorch_training_tpu.analysis import ast_rules

    bad = tmp_path / "bad.py"
    bad.write_text("from jax.experimental import shard_map\n")
    monkeypatch.setattr(ast_rules, "iter_source_files",
                        lambda repo=None: [bad])
    assert main(["check", "--ast-only", "--json"]) == 1
    report = json.loads(capsys.readouterr().out)
    assert report["ok"] is False
    assert report["findings"][0]["rule"] == "shard-map-shim-only"


def test_changed_mode_lints_only_the_git_diff(tmp_path, capsys,
                                              monkeypatch):
    """--changed scopes the PER-FILE rules to the git-changed set but
    keeps whole-repo rules global: a violation in an unchanged file stays
    invisible to the fast loop, a violation in a changed file flips the
    exit code."""
    from distributed_pytorch_training_tpu.analysis import __main__ as cli
    from distributed_pytorch_training_tpu.analysis import ast_rules

    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    bad = tmp_path / "bad.py"
    bad.write_text("from jax.experimental import shard_map\n")
    monkeypatch.setattr(ast_rules, "iter_source_files",
                        lambda repo=None: [clean, bad])

    monkeypatch.setattr(cli, "_changed_source_files", lambda: [clean])
    assert main(["check", "--ast-only", "--changed", "--json"]) == 0
    assert json.loads(capsys.readouterr().out)["ok"] is True

    monkeypatch.setattr(cli, "_changed_source_files", lambda: [bad])
    assert main(["check", "--ast-only", "--changed", "--json"]) == 1
    report = json.loads(capsys.readouterr().out)
    assert report["findings"][0]["rule"] == "shard-map-shim-only"


def test_changed_mode_falls_back_to_full_set_without_git(capsys,
                                                         monkeypatch):
    """A broken git invocation must widen the lint, never narrow it:
    _changed_source_files -> None means the full repo runs."""
    import subprocess

    from distributed_pytorch_training_tpu.analysis import __main__ as cli

    def _no_git(*a, **kw):
        raise FileNotFoundError("git")

    monkeypatch.setattr(subprocess, "run", _no_git)
    assert cli._changed_source_files() is None
    assert main(["check", "--ast-only", "--changed"]) == 0
    assert "0 finding(s)" in capsys.readouterr().out


def test_changed_source_files_intersects_the_linted_set(monkeypatch):
    """Paths git reports that are OUTSIDE the linted tree (deleted
    files, tests, tooling) must not reach the AST engine."""
    import subprocess

    from distributed_pytorch_training_tpu.analysis import __main__ as cli
    from distributed_pytorch_training_tpu.analysis.ast_rules import (
        REPO_ROOT, iter_source_files,
    )

    real = sorted(iter_source_files())[0].relative_to(REPO_ROOT)

    class _Out:
        def __init__(self, stdout):
            self.stdout = stdout

    def _git(cmd, **kw):
        if "diff" in cmd:
            return _Out(f"{real}\nno/such/file.py\nnot_python.txt\n")
        return _Out("")

    monkeypatch.setattr(subprocess, "run", _git)
    changed = cli._changed_source_files()
    assert changed == [(REPO_ROOT / real).resolve()]
